"""Runtime lock-order + thread-lifecycle watchdog.

Since fabriclint v4 the STATIC lock-order rule covers call chains too
(an interprocedural may-held graph; see ``dataflow.Project.lock_graph``
and the ``lock-order`` rule), but it only sees statically resolvable
calls — an acquisition reached through a callback or other unresolvable
indirection still needs a runtime witness.  This module is that
witness: production code creates its coordination locks through
``named_lock``/``named_rlock``, which return plain ``threading`` locks
normally (zero overhead) and instrumented wrappers when
``FABRIC_TPU_LOCKWATCH`` is set (tests/conftest.py sets it, so the whole
tier-1 suite doubles as a lock-order soak test).  The two graphs are
tied together in tier-1: every edge this watchdog observes during a
live commit+snapshot session must be present in the static graph
(runtime ⊆ static, tests/test_lockwatch.py), so the static pass
provably covers what tier-1 exercises.

The wrapper maintains a process-wide acquisition-order graph over lock
ROLES (names, not instances): acquiring B while holding A records the
edge ``A -> B``; if a path ``B -> ... -> A`` already exists, the
acquisition is a deadlock-capable inversion — it is recorded in
``violations`` and raised as ``LockOrderError``.  Mode ``record``
suppresses the raise and only observes: it deliberately does NOT
perturb program behavior, so a genuinely live contended inversion will
still deadlock there (the violation is in ``violations`` for a
debugger/core dump; use the default raise mode to unwedge).  Re-entrant
acquisition of the same lock object is fine (RLock semantics); two
INSTANCES sharing a role name are not ordered against each other (a
documented approximation — role-level cycles are the deadlocks that
have bitten this codebase).  Cross-thread release of a watched plain
Lock (handoff patterns) is unsupported: it raises in the default mode
so the held-stack bookkeeping can never silently rot; record mode logs
it and performs the handoff unperturbed.

THREADWATCH (the thread-lifecycle half): every daemonized worker in the
tree is created through ``spawn_thread``/``spawn_timer`` (fabriclint's
thread-hygiene rule enforces this statically).  Normally they return
plain ``threading.Thread``/``Timer`` objects — zero overhead.  Under
``FABRIC_TPU_THREADWATCH`` (tests/conftest.py sets it) each spawned
thread registers itself in a process-wide live registry on entry,
records any unhandled exception into ``thread_violations`` (a worker
dying silently on a daemon thread is the failure mode that turned
MULTICHIP green runs into rc=134 aborts), and deregisters on exit.
``drain_threads`` joins live registered threads against a deadline and
records stragglers as violations; the session-end fixture in conftest
asserts the ledger is empty, so a worker leaked past its owner's
drain/close fails the suite deterministically instead of aborting the
interpreter ("FATAL: exception not rethrown") at teardown.

Threads register with a ``kind``: ``"worker"`` for bounded jobs that
MUST be gone once their owner drains (flush waiters, snapshot exports,
stream committers) and ``"service"`` for run-until-stopped loops
(acceptors, gossip, orderer consensus).  ``drain_threads`` drains
workers by default — a service leaking past its owner's ``stop()`` is
that owner's bug and is covered by its own close paths, while worker
drains are the interpreter-exit safety property this module exists to
enforce.

CONDITION ORDERING: ``named_condition`` wraps a condition variable in
the same order graph.  ``wait()`` while holding a lock that is an
order-PREDECESSOR of the condition's own lock is flagged (and raises in
the default mode): the wait releases only the condition's lock, so a
waker that follows the canonical order blocks on the held predecessor
and the wait never ends.
"""

from __future__ import annotations

import os
import sys
import threading
import time

from fabric_tpu.devtools import knob_registry

_ENV = "FABRIC_TPU_LOCKWATCH"
_PROFILE_ENV = "FABRIC_TPU_PROFILE"
_PROFILE_FALSY = ("", "0", "false", "off", "no")

# guards the graph + violations; a plain lock that is itself never
# watched, held only for short pure-python critical sections
_state_lock = threading.Lock()
_edges: dict[str, set[str]] = {}
violations: list[dict] = []
_tls = threading.local()


class LockOrderError(RuntimeError):
    """A lock acquisition that closes a cycle in the order graph."""


def enabled() -> bool:
    return knob_registry.raw(_ENV) not in ("", "0", "false", "off")


def _raise_mode() -> bool:
    return knob_registry.raw(_ENV) != "record"


_profmod = None


def _profile_mod():
    """profscope, bound lazily — profile imports spawn_thread from
    this module, so a top-level import would be circular (the
    _trace_note pattern)."""
    global _profmod
    if _profmod is None:
        from fabric_tpu.common import profile

        _profmod = profile
    return _profmod


def _profile_on() -> bool:
    """Is profscope armed (or about to be, via its env knob)?  Checked
    at lock CREATION only; never imports profile on the disarmed
    path."""
    mod = sys.modules.get("fabric_tpu.common.profile")
    if mod is not None:
        try:
            return bool(mod.enabled())
        except Exception:
            return False
    raw = knob_registry.raw(_PROFILE_ENV)
    return raw.strip().lower() not in _PROFILE_FALSY


def _trace_note(kind: str, event: dict) -> None:
    """Mirror a recorded violation into the tracelens flight recorder
    (an instant mark on the active span), so a trace dump shows the
    sanitizer finding in causal context next to the spans that led to
    it.  No-op unless tracing is armed."""
    from fabric_tpu.common import tracing

    if tracing.enabled():
        tracing.instant(kind, **{k: str(v) for k, v in event.items()})


def reset() -> None:
    """Clear the graph and recorded violations (tests)."""
    with _state_lock:
        _edges.clear()
        violations.clear()


def edges() -> dict[str, set[str]]:
    """Snapshot of the acquisition-order graph (tests/diagnostics)."""
    with _state_lock:
        return {k: set(v) for k, v in _edges.items()}


def _held():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []  # [[WatchedLock, count], ...]
    return st


def _find_path(src: str, dst: str) -> list[str] | None:
    """DFS path src -> dst over _edges (caller holds _state_lock)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _edges.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


class WatchedLock:
    """Lock wrapper that feeds the acquisition-order graph.  Wraps a
    Lock or RLock; re-entrancy is tracked by object identity so RLock
    recursion never reports against itself."""

    def __init__(self, name: str, factory=threading.Lock):
        self.name = name
        self._reentrant = factory is threading.RLock
        self._inner = factory()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        st = _held()
        for entry in st:
            if entry[0] is self:
                if not self._reentrant and blocking:
                    # a blocking re-acquire of a plain Lock the SAME
                    # thread already holds can never succeed — diagnose
                    # the self-deadlock instead of wedging inside the
                    # watchdog (a non-blocking try just returns False)
                    bad = {
                        "acquiring": self.name,
                        "holding": self.name,
                        "cycle": [self.name, self.name],
                        "thread": threading.current_thread().name,
                    }
                    with _state_lock:
                        violations.append(bad)
                        _trace_note("lockwatch.violation", bad)
                    if _raise_mode():
                        raise LockOrderError(
                            "self-deadlock: blocking re-acquire of "
                            f"non-reentrant lock {self.name!r}"
                        )
                # re-entrant: same object, no new edge (RLock recursion)
                got = self._inner.acquire(blocking, timeout)
                if got:
                    entry[1] += 1
                return got
        # Check/record ordering BEFORE the (possibly blocking) inner
        # acquire: in a live contended inversion both threads would
        # otherwise sit inside _inner.acquire() forever and the cycle
        # would never be observed — the watchdog must raise instead of
        # inheriting the deadlock it exists to diagnose.  Only an
        # INDEFINITE blocking acquire can wedge forever, so only it
        # pre-records; a try-lock or timed wait records its edges after
        # success — a failed attempt must not poison the graph with an
        # ordering that was never actually held.
        record_now = blocking and timeout == -1
        bad = None
        with _state_lock:
            pending = []
            for held_entry in st:
                h = held_entry[0].name
                if h == self.name:
                    # same ROLE, different instance: role-level ordering
                    # cannot rank an instance against itself; skip
                    continue
                path = _find_path(self.name, h)
                if path is not None:
                    bad = {
                        "acquiring": self.name,
                        "holding": h,
                        "cycle": path + [self.name],
                        "thread": threading.current_thread().name,
                    }
                    violations.append(bad)
                    _trace_note("lockwatch.violation", bad)
                    break
                pending.append(h)
            if bad is None and record_now:
                # commit edges only for an acquisition that will really
                # be attempted — a REFUSED acquisition must not leave
                # partial edges from the held locks scanned before the
                # violating one
                for h in pending:
                    _edges.setdefault(h, set()).add(self.name)
        if bad is not None and _raise_mode():
            raise LockOrderError(
                "lock-order inversion: acquiring "
                f"{bad['acquiring']!r} while holding {bad['holding']!r} "
                f"(established order: {' -> '.join(bad['cycle'])})"
            )
        # profscope contention timing: wall time blocked inside the
        # inner acquire (the wait), plus an acquire timestamp on the
        # held-stack entry so _record_release can report hold time.
        # One enabled() check per acquire when profiling is disarmed.
        prof = _profile_mod() if _profile_on() else None
        if prof is not None and prof.enabled():
            t0 = time.monotonic()
            got = self._inner.acquire(blocking, timeout)
            t1 = time.monotonic()
            if got:
                prof.note_lock_wait(self.name, t1 - t0)
                st.append([self, 1, t1])
        else:
            got = self._inner.acquire(blocking, timeout)
            if got:
                st.append([self, 1])
        if got and not record_now:
            with _state_lock:
                for held_entry in st[:-1]:
                    if held_entry[0].name != self.name:
                        _edges.setdefault(
                            held_entry[0].name, set()
                        ).add(self.name)
        return got

    def release(self) -> None:
        if not self._record_release():
            # threading.Lock legally allows cross-thread release
            # (handoff), but under watch the acquirer's held-stack
            # would keep this lock forever and later acquisitions
            # would record bogus edges
            bad = {
                "event": "cross-thread-release",
                "lock": self.name,
                "thread": threading.current_thread().name,
            }
            with _state_lock:
                violations.append(bad)
                _trace_note("lockwatch.violation", bad)
            if _raise_mode():
                # refuse deterministically (inner stays held: the
                # pattern is unsupported and the test run must fail
                # here, not on a later bogus-edge inversion)
                raise LockOrderError(
                    f"cross-thread release of watched lock {self.name!r} "
                    "(acquired on a different thread); handoff patterns "
                    "are unsupported under FABRIC_TPU_LOCKWATCH"
                )
            # record mode observes without perturbing: perform the
            # legal handoff (the acquirer's stale stack entry is a
            # documented best-effort gap of observe-only mode)
        self._inner.release()

    def __enter__(self) -> "WatchedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:
        return f"<WatchedLock {self.name!r}>"

    def _record_release(self) -> bool:
        """Pop this lock from the current thread's held-stack; False if
        it was not acquired on this thread (cross-thread release).
        Entries carrying an acquire timestamp (profiling was armed at
        acquire) report hold time on the final release."""
        st = _held()
        for i in range(len(st) - 1, -1, -1):
            entry = st[i]
            if entry[0] is self:
                entry[1] -= 1
                if entry[1] == 0:
                    del st[i]
                    if len(entry) == 3:
                        prof = _profile_mod()
                        if prof.enabled():
                            prof.note_lock_hold(
                                self.name, time.monotonic() - entry[2]
                            )
                return True
        return False


def guarded(obj, field: str, *, by: str) -> None:
    """Runtime guard assertion — the dynamic half of fabriclint's
    racecheck.  Production code states, at a hot access site, which
    lock ROLE the static guarded-by map (devtools/guards.py) requires
    for ``obj.field``; a no-op unless FABRIC_TPU_LOCKWATCH, under which
    (tier-1) the calling thread must hold a watched lock with that role
    or the violation lands in the same session-drained ledger as lock
    inversions — so every tier-1 run cross-checks the static guard map
    against what threads actually hold."""
    if not enabled():
        return
    for entry in _held():
        if entry[0].name == by:
            return
    bad = {
        "event": "unguarded-access",
        "object": type(obj).__name__,
        "field": field,
        "required": by,
        "thread": threading.current_thread().name,
    }
    with _state_lock:
        violations.append(bad)
        _trace_note("lockwatch.violation", bad)
    if _raise_mode():
        raise LockOrderError(
            f"unguarded access: {type(obj).__name__}.{field} requires "
            f"lock role {by!r}, which this thread does not hold"
        )


class _ProfiledLock:
    """Plain lock plus profscope contention timing — what named_lock
    returns when profiling is armed but lockwatch is off (production
    profiling runs), so ``lock_wait_seconds{role}`` exists without the
    order-graph overhead.  Per-thread acquire timestamps live in
    ``_tacq`` keyed by thread ident; each thread only ever touches its
    own key, and it does so while HOLDING the inner lock."""

    __slots__ = ("name", "_inner", "_tacq")

    def __init__(self, name: str, factory=threading.Lock):
        self.name = name
        self._inner = factory()
        self._tacq: dict[int, list] = {}

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        prof = _profile_mod()
        if not prof.enabled():
            return self._inner.acquire(blocking, timeout)
        t0 = time.monotonic()
        got = self._inner.acquire(blocking, timeout)
        if got:
            t1 = time.monotonic()
            prof.note_lock_wait(self.name, t1 - t0)
            self._tacq.setdefault(
                threading.get_ident(), []
            ).append(t1)
        return got

    def release(self) -> None:
        ident = threading.get_ident()
        stack = self._tacq.get(ident)
        if stack:
            t1 = stack.pop()
            if not stack:
                self._tacq.pop(ident, None)
            prof = _profile_mod()
            if prof.enabled():
                prof.note_lock_hold(
                    self.name, time.monotonic() - t1
                )
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "_ProfiledLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:
        return f"<_ProfiledLock {self.name!r}>"


def named_lock(name: str):
    """A threading.Lock, watched when FABRIC_TPU_LOCKWATCH is set;
    contention-timed (wrapper only, no order graph) when profscope is
    armed without lockwatch."""
    if enabled():
        return WatchedLock(name, threading.Lock)
    if _profile_on():
        return _ProfiledLock(name, threading.Lock)
    return threading.Lock()


def named_rlock(name: str):
    """A threading.RLock, watched when FABRIC_TPU_LOCKWATCH is set;
    contention-timed when profscope is armed without lockwatch."""
    if enabled():
        return WatchedLock(name, threading.RLock)
    if _profile_on():
        return _ProfiledLock(name, threading.RLock)
    return threading.RLock()


# -- condition-variable wait ordering ----------------------------------------


class WatchedCondition:
    """Condition variable whose wait() participates in the order graph.

    Composed of a WatchedLock (enter/exit bookkeeping feeds the same
    acquisition-order edges as any lock) and a plain Condition sharing
    the SAME underlying lock object.  ``wait()`` first checks the
    thread's held-stack: holding any lock with an established path TO
    this condition's role is a deadlock-capable wait (the waker follows
    the canonical order, blocks on the held predecessor, and the notify
    never comes) — recorded and raised like a lock inversion.  During
    the wait the condition's own entry leaves the held-stack (the wait
    releases the lock) and returns afterwards."""

    def __init__(self, name: str, factory=threading.RLock):
        self.name = name
        self._wlock = WatchedLock(name, factory)
        self._cond = threading.Condition(self._wlock._inner)

    def acquire(self, *a, **k):
        return self._wlock.acquire(*a, **k)

    def release(self):
        self._wlock.release()

    def __enter__(self) -> "WatchedCondition":
        self._wlock.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self._wlock.release()
        return False

    def wait(self, timeout: float | None = None) -> bool:
        st = _held()
        bad = None
        with _state_lock:
            for held_entry in st:
                held = held_entry[0]
                if held is self._wlock or held.name == self.name:
                    continue
                path = _find_path(held.name, self.name)
                if path is not None:
                    bad = {
                        "event": "wait-while-holding-predecessor",
                        "condition": self.name,
                        "holding": held.name,
                        "cycle": path + [self.name],
                        "thread": threading.current_thread().name,
                    }
                    violations.append(bad)
                    _trace_note("lockwatch.violation", bad)
                    break
        if bad is not None and _raise_mode():
            raise LockOrderError(
                f"wait on condition {self.name!r} while holding its "
                f"order-predecessor {bad['holding']!r} (established "
                f"order: {' -> '.join(bad['cycle'])}); the waker cannot "
                "reach notify without the held lock"
            )
        entry = None
        for i in range(len(st) - 1, -1, -1):
            if st[i][0] is self._wlock:
                entry = st.pop(i)
                break
        try:
            return self._cond.wait(timeout)
        finally:
            if entry is not None:
                st.append(entry)

    def wait_for(self, predicate, timeout: float | None = None):
        import time as _time

        endtime = None
        result = predicate()
        while not result:
            if timeout is not None:
                if endtime is None:
                    endtime = _time.monotonic() + timeout
                waittime = endtime - _time.monotonic()
                if waittime <= 0:
                    break
                self.wait(waittime)
            else:
                self.wait()
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()

    def __repr__(self) -> str:
        return f"<WatchedCondition {self.name!r}>"


def named_condition(name: str, factory=threading.RLock):
    """A threading.Condition, wait-order-watched when
    FABRIC_TPU_LOCKWATCH is set."""
    if enabled():
        return WatchedCondition(name, factory)
    return threading.Condition(factory())


# -- threadwatch: thread-lifecycle registry ----------------------------------

_THREAD_ENV = "FABRIC_TPU_THREADWATCH"

_threads_lock = threading.Lock()
_live_threads: dict[int, dict] = {}  # id(thread) -> info
thread_violations: list[dict] = []


def threads_enabled() -> bool:
    return knob_registry.raw(_THREAD_ENV) not in ("", "0", "false", "off")


def reset_threads() -> None:
    """Clear recorded thread violations (tests).  The live registry is
    left alone — threads that exist keep existing."""
    with _threads_lock:
        thread_violations.clear()


def threads_alive(kinds=None) -> list[dict]:
    """Snapshot of live registered threads (name/kind/thread).  Entries
    whose thread ran and finished without the wrapper's deregistration
    (a timer cancelled after start: its callback — and thus the
    wrapper — never executes) are pruned here; entries registered but
    not yet scheduled (ident is None) are kept, which is the whole
    point of registering before start()."""
    with _threads_lock:
        dead = [
            key for key, info in _live_threads.items()
            if not info["thread"].is_alive()
            and info["thread"].ident is not None
        ]
        for key in dead:
            del _live_threads[key]
        return [
            dict(info) for info in _live_threads.values()
            if kinds is None or info["kind"] in kinds
        ]


def _register(t, kind: str) -> None:
    with _threads_lock:
        _live_threads[id(t)] = {"name": t.name, "kind": kind, "thread": t}


def _deregister(t) -> None:
    with _threads_lock:
        _live_threads.pop(id(t), None)


def _wrap_target(cell: dict, kind: str, target):
    """The shared watched-thread body: run the real target, record any
    unhandled exception into the ledger (a daemon worker dying silently
    is how green runs become teardown aborts), deregister on exit."""

    def run(*a, **k):
        t = cell["thread"]
        try:
            target(*a, **k)
        except BaseException as exc:
            with _threads_lock:
                bad = {
                    "event": "unhandled-exception",
                    "thread": t.name,
                    "kind": kind,
                    "error": repr(exc),
                }
                thread_violations.append(bad)
            _trace_note("threadwatch.violation", bad)
            raise
        finally:
            _deregister(t)

    return run


def _registering_start(t, super_start) -> None:
    """start() that registers BEFORE the OS thread exists, so a drain
    sweep can never miss a started-but-not-yet-scheduled worker
    (registering inside the target would leave exactly that window).
    A double-start must not touch the registry: the rollback is only
    for a start() that registered THIS call — deregistering on the
    'already started' RuntimeError would erase the live thread's entry
    and hide it from the drain gate."""
    if t.ident is not None or t.is_alive():
        super_start()  # raises "threads can only be started once"
        return
    _register(t, t._tw_kind)
    try:
        super_start()
    except BaseException:
        _deregister(t)
        raise


class _WatchedThread(threading.Thread):
    _tw_kind = "worker"

    def start(self) -> None:
        _registering_start(self, super().start)


class _WatchedTimer(threading.Timer):
    _tw_kind = "service"

    def start(self) -> None:
        _registering_start(self, super().start)


def spawn_thread(target, *, name: str | None = None, args=(),
                 kwargs=None, daemon: bool = True,
                 kind: str = "worker") -> threading.Thread:
    """Create (NOT start) a daemonized thread through the threadwatch
    seam — the only sanctioned way to daemonize in this tree
    (fabriclint thread-hygiene).  Plain Thread normally; under
    FABRIC_TPU_THREADWATCH the thread registers in the live registry
    when ``start()`` is called (before the OS thread exists), records
    unhandled exceptions into ``thread_violations``, and deregisters on
    exit.

    kind="worker": a bounded job the owner must drain before exit
    (flush waiter, snapshot export, stream committer).
    kind="service": a run-until-stopped loop with its own stop/close
    path (acceptor, gossip, consensus); exempt from the default
    drain_threads sweep."""
    if kind not in ("worker", "service"):
        raise ValueError(f"unknown thread kind {kind!r}")
    kwargs = kwargs or {}
    if not threads_enabled():
        return threading.Thread(
            target=target, name=name, args=args, kwargs=kwargs,
            daemon=daemon,
        )
    cell: dict = {}
    run = _wrap_target(cell, kind, target)
    t = _WatchedThread(
        target=run, name=name, args=args, kwargs=kwargs, daemon=daemon
    )
    t._tw_kind = kind
    cell["thread"] = t
    return t


def spawn_timer(interval: float, function, *, name: str | None = None,
                args=(), kwargs=None,
                kind: str = "service") -> threading.Timer:
    """threading.Timer through the threadwatch seam (daemonized).  A
    timer cancelled after start() skips its callback, so the wrapper's
    deregistration never runs — the registry prunes such dead entries
    on every read (threads_alive), which is exactly the drain
    semantics a cancel-on-halt timer needs."""
    if kind not in ("worker", "service"):
        raise ValueError(f"unknown thread kind {kind!r}")
    kwargs = kwargs or {}
    if not threads_enabled():
        t = threading.Timer(interval, function, args=args, kwargs=kwargs)
        t.daemon = True
        if name:
            t.name = name
        return t
    cell: dict = {}
    run = _wrap_target(cell, kind, function)
    t = _WatchedTimer(interval, run, args=args, kwargs=kwargs)
    t._tw_kind = kind
    t.daemon = True
    if name:
        t.name = name
    cell["thread"] = t
    return t


def tracked_executor(max_workers=None, *, name: str = "executor",
                     kind: str = "worker", initializer=None,
                     initargs=()):
    """A ``concurrent.futures.ThreadPoolExecutor`` through the
    threadwatch seam.  Pool workers are invisible to the session-end
    drain gate when created raw — they are plain threads spawned deep
    inside the executor — so a leaked executor (nobody called
    ``shutdown``) keeps live threads past the tests without anything
    noticing.  Under FABRIC_TPU_THREADWATCH each pool worker registers
    itself (via the executor's initializer hook) in the same live
    registry as spawn_thread workers: the drain gate then joins them,
    and an executor whose owner never shut it down fails the session
    deterministically.  Registry entries of exited workers are pruned
    on read (threads_alive), so a properly shut-down pool leaves no
    residue.  Without threadwatch this returns a plain executor —
    zero overhead."""
    from concurrent.futures import ThreadPoolExecutor

    if kind not in ("worker", "service"):
        raise ValueError(f"unknown thread kind {kind!r}")
    if not threads_enabled():
        return ThreadPoolExecutor(
            max_workers, thread_name_prefix=name,
            initializer=initializer, initargs=initargs,
        )

    def _register_worker(*args):
        _register(threading.current_thread(), kind)
        if initializer is not None:
            initializer(*args)

    return ThreadPoolExecutor(
        max_workers, thread_name_prefix=name,
        initializer=_register_worker, initargs=initargs,
    )


def drain_threads(timeout: float = 10.0, kinds=("worker",)) -> list[str]:
    """Join every live registered thread of the given kinds against one
    shared deadline.  Stragglers are recorded in ``thread_violations``
    (event "drain-timeout") and returned — the session-end gate turns
    them into failures, because a worker still running at interpreter
    exit is precisely the thread the runtime kills mid-kernel."""
    import time as _time

    deadline = _time.monotonic() + timeout
    stragglers: list[str] = []
    for info in threads_alive(kinds):
        t = info["thread"]
        remaining = deadline - _time.monotonic()
        if remaining > 0:
            try:
                t.join(remaining)
            except RuntimeError:
                # registered but its start() is still in flight on the
                # owning thread (registration happens-before start);
                # give the bootstrap a beat and fall through to the
                # is_alive check
                _time.sleep(0.01)
        if t.is_alive():
            stragglers.append(info["name"])
            with _threads_lock:
                thread_violations.append({
                    "event": "drain-timeout",
                    "thread": info["name"],
                    "kind": info["kind"],
                    "timeout": timeout,
                })
    return stragglers


__all__ = [
    "LockOrderError",
    "WatchedLock",
    "WatchedCondition",
    "named_lock",
    "named_rlock",
    "named_condition",
    "guarded",
    "enabled",
    "reset",
    "edges",
    "violations",
    "spawn_thread",
    "spawn_timer",
    "tracked_executor",
    "threads_enabled",
    "threads_alive",
    "thread_violations",
    "reset_threads",
    "drain_threads",
]
