"""Faultline engine + comm-layer chaos tests (ISSUE 6 tentpole): the
zero-overhead no-op contract when no plan is armed, deterministic
triggers/replay, every action kind, the socket io() wrapper, and the
injected comm failures the transports must survive — RPC partial
reads, raft link flaps with backoff'd reconnects and LOUD queue-full
drops, gossip dial backoff, and deliver-stream endpoint rotation."""

import io
import json
import socket
import struct
import time

import pytest

from fabric_tpu.comm.backoff import DecorrelatedBackoff
from fabric_tpu.comm.rpc import RPCClient, RPCError, RPCServer
from fabric_tpu.common.metrics import PrometheusProvider, RaftMetrics
from fabric_tpu.devtools import faultline
from fabric_tpu.ledger import LedgerProvider
from fabric_tpu.orderer.raft.transport import OutboundConn, TCPTransport
from fabric_tpu.protos.common import common_pb2
from fabric_tpu.protos.orderer import raft_pb2 as rpb

from test_group_commit import _write_block


# -- the no-op contract -------------------------------------------------------


def test_unset_means_zero_plan_lookups_on_hot_commit_path(tmp_path):
    """Acceptance: with no plan armed, every fault point on the commit
    path is a no-op — not one plan lookup happens, io() returns the
    socket unchanged, and nothing lands in the trip ledger."""
    if faultline.active():
        pytest.skip(
            "a session-wide plan is armed (FABRIC_TPU_SOAK) — the "
            "zero-overhead contract only applies to unarmed sessions"
        )
    assert not faultline.active()
    before = faultline.lookup_count()
    provider = LedgerProvider(str(tmp_path))
    ledger = provider.open("hot")
    for n in range(4):
        ledger.commit(_write_block(ledger, n, [("cc", f"k{n}", b"v")]))
    provider.close()
    assert faultline.lookup_count() == before
    assert faultline.trips() == []
    sock = object()
    assert faultline.io(sock, "anything") is sock
    buf = io.BytesIO()
    faultline.write("anything", buf, b"ab", b"cd")
    assert buf.getvalue() == b"abcd"


# -- plan parsing & lifecycle -------------------------------------------------


def test_plan_validation_errors():
    for bad in (
        "not json",
        json.dumps([1, 2]),
        {"faults": []},
        {"faults": [{"action": "raise"}]},  # no point
        {"faults": [{"point": "x", "action": "meteor"}]},
        {"faults": [{"point": "x", "error": "NoSuchError"}]},
        {"faults": [{"point": "x", "nth": 1, "every": 2}]},
        {"faults": [{"point": "x", "cut": 1.5}]},
        {"faults": [{"point": "x", "every": 0}]},
        {"faults": [{"point": "x", "nth": 0}]},      # can never fire
        {"faults": [{"point": "x", "nth": "three"}]},
        {"faults": [{"point": "x", "prob": "0.5x"}]},
        {"faults": [{"point": "x", "prob": 25}]},   # percent, not ratio
        {"faults": [{"point": "x", "prob": -0.5}]},
        {"faults": [{"point": "x", "delay_s": "zz"}]},
        {"faults": [{"point": "x", "count": "many"}]},
        {"faults": [{"point": "x", "count": 0}]},
        {"seed": "x", "faults": [{"point": "x"}]},
    ):
        with pytest.raises(faultline.PlanError):
            faultline.Plan(bad)


def test_env_activation_inline_and_file(tmp_path, monkeypatch):
    plan = {"faults": [{"point": "env.x", "action": "delay",
                        "delay_s": 0.0}]}
    monkeypatch.setattr(faultline, "_plan", None)
    monkeypatch.setattr(faultline, "_env_plan", None)
    monkeypatch.setenv("FABRIC_TPU_FAULTLINE", json.dumps(plan))
    faultline._init_from_env()
    assert faultline.active()
    faultline.deactivate()

    path = tmp_path / "plan.json"
    path.write_text(json.dumps(plan))
    monkeypatch.setenv("FABRIC_TPU_FAULTLINE", f"@{path}")
    faultline._init_from_env()
    assert faultline.active()
    faultline.deactivate()
    faultline.reset_trips()


def test_use_plan_drains_on_exit():
    # under FABRIC_TPU_SOAK an ambient plan is legitimately armed:
    # use_plan must restore exactly that state and drain only its own
    ambient = faultline.current_plan()
    with faultline.use_plan({"faults": [
        {"point": "p", "action": "delay", "delay_s": 0.0},
    ]}) as p:
        faultline.point("p")
        own = [t for t in faultline.trips() if t["plan"] == p.label]
        assert len(own) == 1
    assert faultline.current_plan() is ambient
    assert [t for t in faultline.trips() if t["plan"] == p.label] == []


# -- triggers & actions -------------------------------------------------------


def test_nth_every_prob_triggers_deterministic():
    plan = {"seed": 9, "faults": [
        {"point": "a", "action": "delay", "delay_s": 0.0, "nth": 3},
        {"point": "b", "action": "delay", "delay_s": 0.0, "every": 4,
         "count": 100},
        {"point": "c", "action": "delay", "delay_s": 0.0, "prob": 0.3,
         "count": 100},
    ]}

    def run():
        with faultline.use_plan(plan):
            for _ in range(12):
                faultline.point("a")
                faultline.point("b")
                faultline.point("c")
            return faultline.trips()

    t1, t2 = run(), run()
    assert t1 == t2  # same plan + workload -> identical ledger
    assert [t["hit"] for t in t1 if t["point"] == "a"] == [3]  # nth=3 once
    assert [t["hit"] for t in t1 if t["point"] == "b"] == [4, 8, 12]
    c_hits = [t["hit"] for t in t1 if t["point"] == "c"]
    assert c_hits and len(c_hits) < 12  # fired some, not all


def test_multiple_rules_on_one_point_all_count_hits():
    """Every matching rule counts every hit — an earlier rule firing
    must not make a later rule's nth trigger drift (first-fired wins
    the trip, the rest keep counting)."""
    with faultline.use_plan({"faults": [
        {"point": "mr", "action": "delay", "delay_s": 0.0, "nth": 1},
        {"point": "mr", "action": "raise", "error": "RuntimeError",
         "message": "second rule", "nth": 2},
    ]}):
        faultline.point("mr")  # hit 1: rule 0 trips; rule 1 counts it
        with pytest.raises(RuntimeError, match="second rule"):
            faultline.point("mr")  # hit 2: rule 1's nth=2 fires
        assert [(t["rule"], t["hit"]) for t in faultline.trips()] == [
            (0, 1), (1, 2),
        ]


def test_ctx_matching_restricts_rule():
    with faultline.use_plan({"faults": [
        {"point": "s", "ctx": {"stage": "pvt"}, "action": "raise",
         "error": "RuntimeError", "message": "only pvt"},
    ]}):
        faultline.point("s", stage="mvcc")
        faultline.point("s", stage="state")
        with pytest.raises(RuntimeError, match="only pvt"):
            faultline.point("s", stage="pvt")
        [trip] = faultline.trips()
        assert trip["ctx"] == {"stage": "pvt"}


def test_actions_raise_named_errors_and_delay():
    with faultline.use_plan({"faults": [
        {"point": "e1", "action": "raise", "error": "ECONNRESET"},
        {"point": "e2", "action": "raise", "error": "DeviceUnavailable"},
        {"point": "e3", "action": "crash"},
        {"point": "e4", "action": "delay", "delay_s": 0.02, "count": 1},
    ]}):
        with pytest.raises(ConnectionResetError):
            faultline.point("e1")
        with pytest.raises(faultline.DeviceUnavailable):
            faultline.point("e2")
        with pytest.raises(faultline.FaultCrash):
            faultline.point("e3")
        t0 = time.perf_counter()
        faultline.point("e4")
        assert time.perf_counter() - t0 >= 0.015
        faultline.point("e4")  # count exhausted: no delay, no trip
        assert len(faultline.trips()) == 4
    # FaultCrash must NOT be swallowed by broad except Exception
    assert not issubclass(faultline.FaultCrash, Exception)


def test_torn_write_prefix_then_crash():
    buf = io.BytesIO()
    with faultline.use_plan({"faults": [
        {"point": "w", "action": "torn", "cut": 0.25},
    ]}):
        with pytest.raises(faultline.FaultCrash, match="torn write"):
            faultline.write("w", buf, b"AAAA", b"BBBB")
        assert buf.getvalue() == b"AA"  # strict prefix, 8 * 0.25


def test_io_partial_read_then_reset():
    a, b = socket.socketpair()
    try:
        with faultline.use_plan({"faults": [
            {"point": "x.read", "action": "partial", "cut": 0.5,
             "nth": 1},
        ]}):
            wrapped = faultline.io(a, "x")
            assert isinstance(wrapped, faultline._FaultSocket)
            b.sendall(b"0123456789")
            got = wrapped.recv(10)
            assert got == b"01234"  # truncated to half
            with pytest.raises(ConnectionResetError):
                wrapped.recv(10)  # the wrapper is dead now
    finally:
        a.close()
        b.close()


# -- deterministic decorrelated backoff ---------------------------------------


def test_decorrelated_backoff_deterministic_capped_and_resets():
    b1 = DecorrelatedBackoff(base=0.05, cap=1.0, seed=7)
    b2 = DecorrelatedBackoff(base=0.05, cap=1.0, seed=7)
    seq1 = [b1.next() for _ in range(40)]
    seq2 = [b2.next() for _ in range(40)]
    assert seq1 == seq2  # same seed -> same sequence
    assert all(0.05 <= v <= 1.0 for v in seq1)
    # decorrelated jitter may shrink between draws, but trends up:
    # within 40 draws it must have visited well above the base
    assert max(seq1) > 0.4
    b1.reset()
    assert [b1.next() for _ in range(40)] == seq1  # replay after reset
    other = [DecorrelatedBackoff(0.05, 1.0, seed=8).next()
             for _ in range(3)]
    assert other != seq1[:3]  # different peers decorrelate
    # the for_key scheme mixes LOCAL identity into the seed: two nodes
    # dialing the SAME downed peer must not replay identical sequences
    # (their dial windows would align into synchronized bursts)
    a = DecorrelatedBackoff.for_key("node-a->peer:7050")
    b = DecorrelatedBackoff.for_key("node-b->peer:7050")
    assert [a.next() for _ in range(5)] != [b.next() for _ in range(5)]


# -- rpc: injected read faults ------------------------------------------------


def test_rpc_client_partial_read_surfaces_as_error():
    srv = RPCServer()
    srv.register("echo", lambda body, stream: b"E" * 64)
    srv.start()
    try:
        cli = RPCClient(*srv.addr)
        assert cli.call("echo") == b"E" * 64  # healthy first
        with faultline.use_plan({"faults": [
            {"point": "rpc.client.read", "action": "partial",
             "cut": 0.5, "nth": 1},
        ]}):
            with pytest.raises((RPCError, OSError)):
                cli.call("echo")
            assert faultline.trips()
        assert cli.call("echo") == b"E" * 64  # and recovers
    finally:
        srv.stop()


def test_rpc_server_read_reset_drops_connection_cleanly():
    srv = RPCServer()
    srv.register("echo", lambda body, stream: body)
    srv.start()
    try:
        with faultline.use_plan({"faults": [
            {"point": "rpc.server.read", "action": "raise",
             "error": "ECONNRESET", "nth": 1},
        ]}):
            cli = RPCClient(*srv.addr, timeout=2.0)
            with pytest.raises((RPCError, OSError)):
                cli.call("echo", b"x")
            assert faultline.trips()
        # the server loop survived the injected reset
        assert RPCClient(*srv.addr).call("echo", b"ok") == b"ok"
    finally:
        srv.stop()


# -- raft transport: flaps, drops, backoff ------------------------------------


def _step(n: int) -> rpb.StepRequest:
    return rpb.StepRequest(
        channel="ch",
        submit=rpb.SubmitRequest(channel="ch", envelope=b"m%d" % n),
    )


def test_raft_link_flap_reconnects_and_delivers(tmp_path):
    t1 = TCPTransport(1, ("127.0.0.1", 0))
    t2 = TCPTransport(2, ("127.0.0.1", 0))
    got: list[bytes] = []
    t2.set_handler(lambda req: got.append(req.submit.envelope))
    t1.set_peer(2, t2.addr)
    try:
        # prefix wildcard: arms BOTH halves of the io pair — on this
        # outbound link only writes happen, but the chaos-coverage
        # faultmap counts the pin for raft.conn.read too (a wildcard
        # arms whatever the runtime reaches, which is what the pinned
        # registry records)
        with faultline.use_plan({"faults": [
            {"point": "raft.conn.*", "action": "raise",
             "error": "ECONNRESET", "nth": 3},
        ]}):
            # keep sending until delivery resumes through the
            # reconnect: the reset-swallowed message AND messages
            # falling into the armed backoff window are dropped (and
            # counted), raft-tolerated losses both
            deadline = time.monotonic() + 10
            sent = 0
            while time.monotonic() < deadline and len(got) < 9:
                t1.send(1, 2, _step(sent))
                sent += 1
                time.sleep(0.05)
            flapped = [t for t in faultline.trips()
                       if t["point"] == "raft.conn.write"]
            assert flapped  # the link really was reset mid-traffic
        assert len(got) >= 9  # traffic flowed again after the flap
        # the flap's losses were counted, not silent
        with t1._lock:
            conn = t1._peers[2]
        assert conn.dropped >= 1
    finally:
        t1.close()
        t2.close()


def test_raft_send_drop_logs_once_per_episode_and_counts():
    import logging

    from fabric_tpu.common.flogging import must_get_logger

    prov = PrometheusProvider()
    metrics = RaftMetrics(prov)
    # a port from an immediately-closed listener: nothing dials it, and
    # the sender thread is stopped before the queue is filled
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    dead_addr = probe.getsockname()
    probe.close()
    conn = OutboundConn(dead_addr, peer_id=7, metrics=metrics,
                        queue_size=1)
    conn._stop.set()
    conn._thread.join(timeout=3)
    records: list[logging.LogRecord] = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    logger = must_get_logger("orderer.consensus.transport")
    cap = Capture()
    logger.addHandler(cap)
    try:
        conn.send(b"a")      # fills the queue
        conn.send(b"b")      # drop 1: logs
        conn.send(b"c")      # drop 2: same episode, silent
        assert conn.dropped == 2
        assert len(records) == 1
        assert "raft_send_dropped_total" in records[0].getMessage()
        # episode resets on a successful enqueue
        conn.q.get_nowait()
        conn.send(b"d")      # fits: episode over
        conn.send(b"e")      # drop 3: NEW episode, logs again
        assert conn.dropped == 3
        assert len(records) == 2
        exposed = prov.registry.expose()
        assert 'raft_send_dropped_total{dest="7"} 3' in exposed
    finally:
        logger.removeHandler(cap)
        conn.close()


def test_raft_reconnect_backoff_gates_dials(monkeypatch):
    """While a peer is down, dials happen per backoff window — not per
    queued message."""
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    dead_addr = probe.getsockname()
    probe.close()
    with faultline.use_plan({"faults": [
        # counting rule: one zero-delay trip per dial attempt
        {"point": "raft.connect", "action": "delay", "delay_s": 0.0,
         "count": 10000},
    ]}):
        conn = OutboundConn(dead_addr, peer_id=3)
        try:
            for n in range(50):
                conn.send(b"m%d" % n)
            time.sleep(1.0)
            dials = len([t for t in faultline.trips()
                         if t["point"] == "raft.connect"])
            # 50 sends in ~1s against a dead peer: without the gate
            # every message would dial; with backoff (base 50ms,
            # growing) only a handful of windows fit
            assert 1 <= dials < 15
            # and the gate-window discards are NOT silent: every
            # dropped message counts toward the loud-drop ledger
            assert conn.dropped > 0
        finally:
            conn.close()


# -- deliver client: rotation + backoff reset/cap (satellite) -----------------


def _block(num: int) -> common_pb2.Block:
    blk = common_pb2.Block()
    blk.header.number = num
    return blk


def test_deliver_rotation_backoff_resets_and_caps():
    """The shuffled-endpoint loop must grow its backoff while injected
    stream failures persist (capped at max_backoff_s), rotate across
    endpoints, and reset to 0.1s after a successfully delivered block
    — driven by faultline-injected stream failures, no monkeypatching."""
    from fabric_tpu.peer.deliverclient import DeliverClient

    committed: list[int] = []
    tried: list[str] = []

    def endpoint(name: str):
        def connect(start: int):
            tried.append(name)
            for n in range(start, 3):
                yield _block(n)
        return connect

    dc = DeliverClient(
        "ch",
        [endpoint("a"), endpoint("b")],
        height_fn=lambda: len(committed),
        sink=lambda seq, raw: committed.append(seq),
        max_backoff_s=0.25,
    )
    with faultline.use_plan({"faults": [
        # the first four read attempts die: forces three backoff
        # growth steps (0.1 -> 0.2 -> cap 0.25) across rotations
        {"point": "deliver.read", "action": "raise", "error": "OSError",
         "every": 1, "count": 4},
        # zero-delay counting rule: one trip per reconnect episode
        {"point": "deliver.reconnect", "action": "delay",
         "delay_s": 0.0, "count": 10000},
    ]}):
        dc.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and len(committed) < 3:
            time.sleep(0.02)
        # let the loop take one more healthy lap so the post-success
        # backoff value is recorded
        time.sleep(0.3)
        dc.stop()
        reconnects = [t for t in faultline.trips()
                      if t["point"] == "deliver.reconnect"]
        assert len(reconnects) >= 4
    assert committed == [0, 1, 2]
    assert set(tried) == {"a", "b"}  # rotation really alternated
    log = dc.backoff_log
    assert log[0] == 0.1                      # starts at the floor
    assert max(log) == 0.25                   # capped at max_backoff_s
    assert 0.2 in log                         # and actually grew
    # reset after the successful stream: a 0.1 entry right after a
    # grown one (idle caught-up laps re-grow toward the cap afterwards,
    # which is the loop's deliberate polling behavior)
    assert any(
        log[i] == 0.1 and log[i - 1] >= 0.2 for i in range(1, len(log))
    )


# -- gossip: dial backoff under injected failure ------------------------------

def test_gossip_dial_fault_backs_off_and_recovers():
    from fabric_tpu.gossip.comm import TCPGossipComm
    from fabric_tpu.protos.gossip import message_pb2 as gpb

    recv = TCPGossipComm(("127.0.0.1", 0), b"id-recv")
    send = TCPGossipComm(("127.0.0.1", 0), b"id-send")
    seen: list[bytes] = []
    recv.subscribe(lambda rm: seen.append(rm.msg.alive_msg.membership.endpoint))
    try:
        msg = gpb.GossipMessage()
        msg.alive_msg.membership.endpoint = "e0"
        with faultline.use_plan({"faults": [
            {"point": "gossip.dial", "action": "raise",
             "error": "ConnectionRefusedError", "nth": 1},
        ]}):
            send.send(recv.endpoint, msg)  # first dial dies
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not faultline.trips():
                time.sleep(0.02)
            assert faultline.trips()
        # subsequent messages get through once the fault clears (the
        # first one may have been consumed by the failed dial attempt)
        deadline = time.monotonic() + 10
        n = 1
        while time.monotonic() < deadline and not seen:
            m = gpb.GossipMessage()
            m.alive_msg.membership.endpoint = "e%d" % n
            send.send(recv.endpoint, m)
            n += 1
            time.sleep(0.05)
        assert seen
    finally:
        send.close()
        recv.close()


def test_gossip_conn_fault_mid_stream_reconnects():
    """A reset INSIDE an established gossip link (the ``gossip.conn``
    io pair, armed by prefix wildcard — same rationale as the raft
    link-flap plan: the wildcard arms whichever half the runtime
    reaches) — the sender's reconnect-per-message loop restores
    delivery, gossip's loss tolerance absorbing the reset-swallowed
    frame."""
    from fabric_tpu.gossip.comm import TCPGossipComm
    from fabric_tpu.protos.gossip import message_pb2 as gpb

    recv = TCPGossipComm(("127.0.0.1", 0), b"id-recv")
    send = TCPGossipComm(("127.0.0.1", 0), b"id-send")
    seen: list[str] = []
    recv.subscribe(lambda rm: seen.append(rm.msg.alive_msg.membership.endpoint))
    try:
        with faultline.use_plan({"faults": [
            {"point": "gossip.conn.*", "action": "raise",
             "error": "ECONNRESET", "nth": 2},
        ]}):
            deadline = time.monotonic() + 10
            n = 0
            while time.monotonic() < deadline and (
                not faultline.trips() or len(seen) < 3
            ):
                m = gpb.GossipMessage()
                m.alive_msg.membership.endpoint = "e%d" % n
                send.send(recv.endpoint, m)
                n += 1
                time.sleep(0.05)
            tripped = [t for t in faultline.trips()
                       if t["point"].startswith("gossip.conn.")]
            assert tripped, "the link was never reset"
        assert len(seen) >= 3  # traffic flowed again after the reset
    finally:
        send.close()
        recv.close()


# -- multichip dryrun under a device-loss plan (ISSUE 7 satellite) -----------


@pytest.mark.slow
def test_dryrun_multichip_device_loss_breaker_rc0():
    """ROADMAP faultline candidate closed: the multichip dryrun with a
    seeded plan that kills one device's collect mid-dispatch must (a)
    fail over to the host oracle with correct verdicts, (b) open the
    TPUCSP circuit breaker and serve follow-up traffic breaker-routed
    (dryrun asserts both internally when a plan is armed), and (c)
    still exit rc=0 through NORMAL teardown with the threadwatch
    ledger empty — chaos must not resurrect the rc=134 class."""
    import os
    import subprocess
    import sys
    import textwrap

    pytest.importorskip(
        "cryptography", reason="dryrun builds a 5-org world"
    )
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = textwrap.dedent("""
        from fabric_tpu.devtools import faultline

        assert faultline.active(), "env fault plan was not armed"

        import __graft_entry__

        __graft_entry__.dryrun_multichip(2)

        trips = faultline.trips()
        assert any(t["point"] == "tpu.collect" for t in trips), trips

        from fabric_tpu.devtools import lockwatch

        assert not lockwatch.thread_violations, (
            repr(lockwatch.thread_violations)
        )
        stragglers = lockwatch.drain_threads(timeout=30.0)
        assert not stragglers, repr(stragglers)
        print("DEVICE-LOSS-OK", flush=True)
    """)
    plan = json.dumps({
        "seed": 7,
        "faults": [{
            "point": "tpu.collect", "action": "raise",
            "error": "DeviceUnavailable", "nth": 1,
        }],
    })
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "FABRIC_TPU_LOCKWATCH": "1",
        "FABRIC_TPU_THREADWATCH": "1",
        "FABRIC_TPU_FAULTLINE": plan,
        "FABRIC_TPU_BREAKER_THRESHOLD": "1",
    })
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=root, env=env, capture_output=True, text=True,
        timeout=1500.0,
    )
    assert proc.returncode == 0, (
        f"device-loss dryrun exited rc={proc.returncode}\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-4000:]}"
    )
    assert "DEVICE-LOSS-OK" in proc.stdout


# -- use_plan nesting / re-arm semantics (ISSUE 8 satellite) ------------------


def test_use_plan_nesting_inner_wins_outer_restored_with_state():
    """Soak + test-local composition: the inner plan wins for its
    scope, trips are tagged per plan label, and the OUTER plan comes
    back with its trigger state intact (hit counters keep counting
    from where they were, not from zero)."""
    ambient = faultline.current_plan()  # the soak plan, if env-armed
    outer_plan = {"seed": 1, "label": "outer", "faults": [
        {"point": "nest", "action": "raise", "error": "RuntimeError",
         "message": "outer fired", "nth": 2},
    ]}
    with faultline.use_plan(outer_plan) as outer:
        faultline.point("nest")  # outer hit 1: nth=2 not yet
        with faultline.use_plan({"seed": 2, "label": "inner", "faults": [
            {"point": "nest", "action": "delay", "delay_s": 0.0,
             "count": 100},
        ]}):
            # the inner plan WINS: outer's nth=2 must not fire here
            for _ in range(3):
                faultline.point("nest")
            labels = [t["plan"] for t in faultline.trips()]
            assert labels == ["inner", "inner", "inner"]
        # inner scope exited: its trips drained, outer restored
        assert faultline.current_plan() is outer
        assert faultline.trips() == []
        with pytest.raises(RuntimeError, match="outer fired"):
            faultline.point("nest")  # outer hit 2: nth=2 fires NOW
        [trip] = faultline.trips()
        assert trip["plan"] == "outer" and trip["hit"] == 2
    assert faultline.current_plan() is ambient
    assert faultline.trips() == []


def test_use_plan_nested_exit_keeps_outer_trips():
    with faultline.use_plan({"label": "outer", "faults": [
        {"point": "keep", "action": "delay", "delay_s": 0.0,
         "count": 10},
    ]}):
        faultline.point("keep")
        with faultline.use_plan({"label": "inner", "faults": [
            {"point": "keep2", "action": "delay", "delay_s": 0.0},
        ]}):
            faultline.point("keep2")
        # ONLY the inner trips drained on its exit
        assert [t["plan"] for t in faultline.trips()] == ["outer"]


# -- registry + observe + guard (ISSUE 8 tentpole surface) --------------------


def test_registry_self_registers_under_observe_and_plans():
    faultline.reset_registry()
    with faultline.observe():
        faultline.point("reg.a", stage="one")
        faultline.point("reg.a", stage="two")
        assert faultline.guard("reg.g") is True
        buf = io.BytesIO()
        faultline.write("reg.w", buf, b"x")
        a, b = socket.socketpair()
        try:
            wrapped = faultline.io(a, "reg.sock")
            assert isinstance(wrapped, faultline._FaultSocket)
            b.sendall(b"z")
            wrapped.recv(1)
        finally:
            a.close()
            b.close()
        assert faultline.trips() == []  # observer never fires
    reg = faultline.registry()
    assert reg["reg.a"]["kinds"] == ["point"]
    assert reg["reg.a"]["ctx"]["stage"] == ["one", "two"]
    assert reg["reg.g"]["kinds"] == ["guard"]
    assert reg["reg.w"]["kinds"] == ["write"]
    assert reg["reg.sock.read"]["kinds"] == ["io"]
    faultline.reset_registry()


def test_registry_untouched_while_unarmed():
    if faultline.active():
        pytest.skip(
            "a session-wide plan is armed (FABRIC_TPU_SOAK) — every "
            "point hit registers by design"
        )
    faultline.reset_registry()
    faultline.point("quiet.a")
    assert faultline.guard("quiet.g") is True
    assert faultline.registry() == {}


def test_guard_skip_action_and_counts():
    with faultline.use_plan({"faults": [
        {"point": "g.trunc", "action": "skip", "count": 2},
    ]}):
        assert faultline.guard("g.trunc") is False
        assert faultline.guard("g.trunc") is False
        assert faultline.guard("g.trunc") is True  # count exhausted
        assert len(faultline.trips()) == 2
    # other actions at a guard point still execute
    with faultline.use_plan({"faults": [
        {"point": "g.x", "action": "raise", "error": "OSError"},
    ]}):
        with pytest.raises(OSError):
            faultline.guard("g.x")
    # a skip rule reaching a bare point() degrades to a loud raise
    with faultline.use_plan({"faults": [
        {"point": "g.y", "action": "skip"},
    ]}):
        with pytest.raises(faultline.FaultInjected, match="non-data"):
            faultline.point("g.y")


def test_wildcard_points_match_prefixes():
    with faultline.use_plan({"faults": [
        {"point": "rpc.*", "action": "delay", "delay_s": 0.0,
         "count": 100},
        {"point": "*", "action": "delay", "delay_s": 0.0, "nth": 3},
    ]}):
        faultline.point("rpc.accept")   # rpc.* trips; * counts hit 1
        faultline.point("ledger.x")     # * hit 2
        faultline.point("other.y")      # * hit 3: fires
        trips = faultline.trips()
        assert [(t["point"], t["rule"]) for t in trips] == [
            ("rpc.accept", 0), ("other.y", 1),
        ]


# -- backoff edge cases (ISSUE 8 satellite) -----------------------------------


def test_backoff_cap_saturation_never_exceeds_cap():
    b = DecorrelatedBackoff(base=0.05, cap=0.4, seed=21)
    seq = [b.next() for _ in range(200)]
    assert all(0.05 <= v <= 0.4 for v in seq)
    # the sequence SATURATES: once grown, draws keep touching the cap
    assert seq.count(0.4) >= 3
    # and decorrelated jitter still moves BELOW the cap afterwards
    # (uniform(base, 3*prev) can undershoot — that is the jitter)
    first_cap = seq.index(0.4)
    assert any(v < 0.4 for v in seq[first_cap + 1:])


def test_backoff_reset_after_success_is_idempotent():
    b = DecorrelatedBackoff(base=0.05, cap=1.0, seed=5)
    first = b.next()
    b.reset()
    b.reset()  # pristine: the no-op path
    assert b.next() == first  # replays from the start
    b.reset()
    seq = [b.next() for _ in range(10)]
    b.reset()
    assert [b.next() for _ in range(10)] == seq


def test_backoff_per_address_seeds_distinct_but_deterministic():
    addrs = ["peer0:7050", "peer1:7050", "peer2:7050"]
    seqs = {}
    for addr in addrs:
        key = f"node-a->{addr}"
        s1 = [DecorrelatedBackoff.for_key(key).next() for _ in range(6)]
        s2 = [DecorrelatedBackoff.for_key(key).next() for _ in range(6)]
        assert s1 == s2  # same key: deterministic replay
        seqs[addr] = s1
    # distinct addresses decorrelate
    vals = list(seqs.values())
    assert vals[0] != vals[1] and vals[1] != vals[2] and vals[0] != vals[2]
