"""Text DSL for signature policies: AND / OR / OutOf over MSP principals.

Reference surface: common/policydsl/policyparser.go (`AND('Org1.member',
OR('Org2.admin', 'Org3.peer'))`, `OutOf(2, ...)`).  Independent
recursive-descent implementation (the reference uses an expression-eval
library); same accepted language, same proto output shape.
"""

from __future__ import annotations

import re

from fabric_tpu.protos.common import policies_pb2
from fabric_tpu.protos.msp import msp_principal_pb2 as mp

_ROLES = {
    "member": mp.MSPRole.MEMBER,
    "admin": mp.MSPRole.ADMIN,
    "client": mp.MSPRole.CLIENT,
    "peer": mp.MSPRole.PEER,
    "orderer": mp.MSPRole.ORDERER,
}

_TOKEN_RE = re.compile(
    r"\s*(?:(?P<name>[A-Za-z]\w*)|(?P<num>\d+)|(?P<str>'[^']*'|\"[^\"]*\")|(?P<punct>[(),]))"
)


class DSLError(Exception):
    pass


def _tokenize(src: str):
    pos = 0
    out = []
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if not m or m.end() == pos:
            if src[pos:].strip():
                raise DSLError(f"unexpected input at: {src[pos:pos+20]!r}")
            break
        pos = m.end()
        if m.group("name"):
            out.append(("name", m.group("name")))
        elif m.group("num"):
            out.append(("num", int(m.group("num"))))
        elif m.group("str"):
            out.append(("str", m.group("str")[1:-1]))
        else:
            out.append(("punct", m.group("punct")))
    return out


class _Parser:
    def __init__(self, tokens):
        self.tokens = tokens
        self.i = 0

    def peek(self):
        return self.tokens[self.i] if self.i < len(self.tokens) else ("eof", None)

    def next(self):
        tok = self.peek()
        self.i += 1
        return tok

    def expect(self, kind, value=None):
        tok = self.next()
        if tok[0] != kind or (value is not None and tok[1] != value):
            raise DSLError(f"expected {value or kind}, got {tok}")
        return tok

    def parse_expr(self):
        kind, value = self.next()
        if kind == "str":
            return ("principal", value)
        if kind != "name":
            raise DSLError(f"expected function or principal, got {value!r}")
        fn = value.lower()
        self.expect("punct", "(")
        args = []
        if self.peek() != ("punct", ")"):
            while True:
                if fn == "outof" and not args:
                    k, v = self.next()
                    if k != "num":
                        raise DSLError("OutOf requires a leading integer")
                    args.append(("n", v))
                else:
                    args.append(self.parse_expr())
                if self.peek() == ("punct", ","):
                    self.next()
                    continue
                break
        self.expect("punct", ")")
        if fn == "and":
            return ("outof", len(args), args)
        if fn == "or":
            return ("outof", 1, args)
        if fn == "outof":
            if not args or args[0][0] != "n":
                raise DSLError("OutOf requires a leading integer")
            return ("outof", args[0][1], args[1:])
        raise DSLError(f"unknown function {fn!r}")


def _principal_from_string(spec: str) -> mp.MSPPrincipal:
    if "." not in spec:
        raise DSLError(f"principal {spec!r} must look like 'MSP.role'")
    mspid, role = spec.rsplit(".", 1)
    role = role.lower()
    if role not in _ROLES:
        raise DSLError(f"unknown role {role!r} (want one of {sorted(_ROLES)})")
    return mp.MSPPrincipal(
        principal_classification=mp.MSPPrincipal.ROLE,
        principal=mp.MSPRole(
            msp_identifier=mspid, role=_ROLES[role]
        ).SerializeToString(),
    )


def from_string(src: str) -> policies_pb2.SignaturePolicyEnvelope:
    """Parse the DSL into a SignaturePolicyEnvelope with deduped principals."""
    parser = _Parser(_tokenize(src))
    tree = parser.parse_expr()
    if parser.peek()[0] != "eof":
        raise DSLError("trailing input after policy expression")
    identities: list[mp.MSPPrincipal] = []
    index: dict[bytes, int] = {}

    def build(node) -> policies_pb2.SignaturePolicy:
        if node[0] == "principal":
            principal = _principal_from_string(node[1])
            key = principal.SerializeToString()
            if key not in index:
                index[key] = len(identities)
                identities.append(principal)
            return policies_pb2.SignaturePolicy(signed_by=index[key])
        _, n, children = node
        if n > len(children):
            raise DSLError(f"OutOf({n}) with only {len(children)} sub-policies")
        return policies_pb2.SignaturePolicy(
            n_out_of=policies_pb2.SignaturePolicy.NOutOf(
                n=n, rules=[build(c) for c in children]
            )
        )

    rule = build(tree)
    return policies_pb2.SignaturePolicyEnvelope(
        version=0, rule=rule, identities=identities
    )


__all__ = ["from_string", "DSLError"]
