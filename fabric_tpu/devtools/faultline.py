"""faultline — deterministic fault injection across comm/ledger/TPU.

The lockwatch/threadwatch sanitizers (PRs 3-4) proved that robustness
claims only hold when a machine can exercise them.  This module is the
failure-side counterpart: named fault points compiled into the
failure-critical layers (`comm/rpc.py`, `gossip/comm.py`,
`orderer/raft/transport.py`, `peer/deliverclient.py`,
`ledger/kvstore.py`+`blkstorage.py`+`kvledger.py`,
`csp/tpu/provider.py`) that are ZERO-OVERHEAD no-ops unless a plan is
armed — `point()` is a module-global load and an `is None` test, and
`io()` hands back the very socket it was given — so production and
tier-1 hot paths pay nothing.

A PLAN is a JSON document (inline in ``FABRIC_TPU_FAULTLINE``, or
``@/path/to/plan.json``, or passed to :func:`activate` /
:func:`use_plan` by tests)::

    {"seed": 7, "faults": [
        {"point": "kvstore.txn", "action": "crash", "nth": 2},
        {"point": "raft.conn.write", "action": "raise",
         "error": "ECONNRESET", "every": 5},
        {"point": "tpu.collect", "action": "raise",
         "error": "DeviceUnavailable", "count": 3},
        {"point": "blkstorage.file_append", "action": "torn",
         "cut": 0.4, "nth": 1},
        {"point": "commit.stage", "ctx": {"stage": "pvt"},
         "action": "crash", "nth": 1},
        {"point": "rpc.client.read", "action": "partial",
         "prob": 0.25}
    ]}

Actions: ``raise`` (named error class, default :class:`FaultInjected`),
``crash`` (:class:`FaultCrash` — simulated process death, a
BaseException so no recovery/cleanup handler may swallow it), ``delay``
(``delay_s`` seconds), ``torn`` (at :func:`write` points: a prefix of
the payload lands, then FaultCrash — torn-write-then-crash), and
``partial`` (at :func:`io` read points: a truncated read, then the
connection is reset).  Triggers: ``nth`` (fire on the Nth matching
hit), ``every`` (every Kth), ``prob`` (seeded probability), default
every hit; ``count`` caps total trips (default 1 for ``nth``,
unlimited otherwise); ``ctx`` restricts to call sites whose keyword
context matches (e.g. a specific commit stage).  All randomness comes
from ``random.Random(f"{seed}:{rule_index}")`` — never wall-clock — so a
chaos run REPLAYS exactly: the same plan over the same workload yields
an identical trip ledger.

Every fired fault is recorded in a process-wide TRIP LEDGER
(:func:`trips`), queryable by tests and drained via conftest like the
threadwatch ledger: :func:`use_plan` clears it on exit, and the
session-end gate asserts no plan is still armed and no trips were left
unexamined.
"""

from __future__ import annotations

import contextlib
import json
import os
import random
import threading
import time

_ENV = "FABRIC_TPU_FAULTLINE"


class PlanError(ValueError):
    """A fault plan that does not validate."""


class FaultInjected(OSError):
    """Generic injected failure.  An OSError so the transports' and
    storage layers' real error paths route it like the failures it
    stands in for."""


class FaultCrash(BaseException):
    """Simulated process death.  Deliberately NOT an Exception: a broad
    ``except Exception`` recovery handler must never swallow it, and the
    ledger's group-rollback seam explicitly skips cleanup for it
    (``faultline.is_crash``) — a real crash gets no unwind, so the test
    that catches this and reopens the store exercises the REAL recovery
    path, not the graceful one."""


class DeviceUnavailable(RuntimeError):
    """Injected accelerator loss (the TPU device vanished mid-flush)."""


_ERRORS = {
    "FaultInjected": FaultInjected,
    "FaultCrash": FaultCrash,
    "OSError": OSError,
    "IOError": OSError,
    "ConnectionResetError": ConnectionResetError,
    "ECONNRESET": ConnectionResetError,
    "BrokenPipeError": BrokenPipeError,
    "ConnectionRefusedError": ConnectionRefusedError,
    "TimeoutError": TimeoutError,
    "RuntimeError": RuntimeError,
    "ValueError": ValueError,
    "DeviceUnavailable": DeviceUnavailable,
}

_ACTIONS = ("raise", "crash", "delay", "torn", "partial")

# the armed plan; point()/io()/write() fast paths test ONLY this global
_plan = None
_state_lock = threading.Lock()

# process-wide trip ledger (survives deactivate; use_plan drains it)
_trips: list[dict] = []
_trips_lock = threading.Lock()

# plan consultations — stays 0 while no plan is armed, which is the
# acceptance test for "every fault point is a no-op when unset"
_lookups = [0]


class _Rule:
    """One fault specification, with its deterministic trigger state."""

    def __init__(self, index: int, spec: dict, seed: int):
        if not isinstance(spec, dict):
            raise PlanError(f"fault #{index} is not an object")
        point = spec.get("point")
        if not isinstance(point, str) or not point:
            raise PlanError(f"fault #{index}: missing point name")
        self.index = index
        self.point = point
        self.action = spec.get("action", "raise")
        if self.action not in _ACTIONS:
            raise PlanError(
                f"fault #{index}: unknown action {self.action!r} "
                f"(one of {', '.join(_ACTIONS)})"
            )
        self.error = spec.get("error", "FaultInjected")
        if self.error not in _ERRORS:
            raise PlanError(
                f"fault #{index}: unknown error {self.error!r} "
                f"(one of {', '.join(sorted(_ERRORS))})"
            )
        self.message = spec.get(
            "message", f"faultline: injected fault at {point}"
        )
        try:
            self.delay_s = float(spec.get("delay_s", 0.01))
            self.cut = float(spec.get("cut", 0.5))
        except (TypeError, ValueError):
            raise PlanError(
                f"fault #{index}: delay_s/cut must be numbers"
            ) from None
        if not 0.0 <= self.cut <= 1.0:
            raise PlanError(f"fault #{index}: cut must be in [0, 1]")
        ctx = spec.get("ctx") or {}
        if not isinstance(ctx, dict):
            raise PlanError(f"fault #{index}: ctx must be an object")
        self.ctx = ctx
        def typed(key, conv, minimum=None):
            """Coerce a trigger field at PARSE time — a bad value must
            be a PlanError at activate(), not a TypeError mid-commit
            inside the injected production path."""
            v = spec.get(key)
            if v is None:
                return None
            try:
                v = conv(v)
            except (TypeError, ValueError):
                raise PlanError(
                    f"fault #{index}: {key} must be a {conv.__name__}"
                ) from None
            if minimum is not None and v < minimum:
                raise PlanError(
                    f"fault #{index}: {key} must be >= {minimum}"
                )
            return v

        self.nth = typed("nth", int, minimum=1)
        self.every = typed("every", int, minimum=1)
        self.prob = typed("prob", float)
        if self.prob is not None and not 0.0 <= self.prob <= 1.0:
            raise PlanError(f"fault #{index}: prob must be in [0, 1]")
        if sum(x is not None for x in (self.nth, self.every, self.prob)) > 1:
            raise PlanError(
                f"fault #{index}: nth/every/prob are mutually exclusive"
            )
        default_count = 1 if self.nth is not None else None
        self.count = typed("count", int, minimum=1)
        if self.count is None:
            self.count = default_count
        self.hits = 0
        self.trips = 0
        # seeded from the PLAN, never wall-clock: chaos runs replay
        self._rng = random.Random(f"{seed}:{index}")

    def matches(self, ctx: dict) -> bool:
        return all(ctx.get(k) == v for k, v in self.ctx.items())

    def fire(self) -> bool:
        """Count a matching hit and decide whether this rule's trigger
        fires on it (caller holds the plan lock).  Does NOT record the
        trip — when several rules on one point fire on the same hit,
        only the first in plan order wins and Plan.visit records it."""
        self.hits += 1
        if self.count is not None and self.trips >= self.count:
            return False
        if self.nth is not None:
            return self.hits == self.nth
        if self.every is not None:
            return self.hits % self.every == 0
        if self.prob is not None:
            return self._rng.random() < self.prob
        return True

    def execute(self):
        """Perform the point-level action: raise, crash, or delay.
        torn/partial reached through a bare point() cannot honor their
        data-level semantics, so they degrade to a loud raise."""
        if self.action == "delay":
            if self.delay_s > 0:
                time.sleep(self.delay_s)
            return
        if self.action == "crash":
            raise FaultCrash(self.message)
        if self.action == "raise":
            raise _ERRORS[self.error](self.message)
        raise FaultInjected(
            f"{self.message} ({self.action} fault at a non-data point)"
        )

    def cut_len(self, n: int) -> int:
        """Strict-prefix length for torn/partial payloads of n bytes."""
        if n <= 0:
            return 0
        return max(0, min(n - 1, int(n * self.cut)))


class Plan:
    """A parsed, armed fault schedule."""

    def __init__(self, spec):
        if isinstance(spec, (str, bytes)):
            try:
                spec = json.loads(spec)
            except ValueError as exc:
                raise PlanError(f"plan is not valid JSON: {exc}") from exc
        if not isinstance(spec, dict):
            raise PlanError("plan must be a JSON object")
        try:
            self.seed = int(spec.get("seed", 0))
        except (TypeError, ValueError):
            raise PlanError("plan seed must be an integer") from None
        faults = spec.get("faults")
        if not isinstance(faults, list) or not faults:
            raise PlanError("plan must carry a non-empty 'faults' list")
        self.rules: list[_Rule] = [
            _Rule(i, fs, self.seed) for i, fs in enumerate(faults)
        ]
        self._by_point: dict[str, list[_Rule]] = {}
        for r in self.rules:
            self._by_point.setdefault(r.point, []).append(r)
        self._lock = threading.Lock()

    def visit(self, name: str, ctx: dict):
        """Consult the schedule for one hit of `name`; returns the
        tripped rule (trip already recorded in the ledger) or None.
        EVERY matching rule counts the hit — a later rule's nth/every
        trigger must not drift just because an earlier rule fired on
        the same hit; when several fire at once the first in plan
        order wins and only it records a trip."""
        winner = None
        with self._lock:
            _lookups[0] += 1
            for r in self._by_point.get(name, ()):
                if r.matches(ctx) and r.fire() and winner is None:
                    winner = r
            if winner is not None:
                winner.trips += 1
                rec = {
                    "point": name,
                    "action": winner.action,
                    "rule": winner.index,
                    "hit": winner.hits,
                    "trip": winner.trips,
                }
                if ctx:
                    rec["ctx"] = dict(ctx)
                with _trips_lock:
                    _trips.append(rec)
        return winner


# -- fault points -------------------------------------------------------------


def point(name: str, **ctx) -> None:
    """A named fault point.  No plan armed: a global load + None test.
    Armed: consult the schedule; a tripped rule raises (raise/crash) or
    delays in place."""
    p = _plan
    if p is None:
        return
    r = p.visit(name, ctx)
    if r is not None:
        r.execute()


def write(name: str, fh, *chunks: bytes, **ctx) -> None:
    """File-write fault point: honors torn-write-then-crash.  No plan:
    writes the chunks straight through (no concatenation, no copy).  A
    tripped ``torn`` rule writes a strict prefix of the joined payload,
    flushes it so the tear is really on disk, and raises
    :class:`FaultCrash`; other actions execute BEFORE anything is
    written (crash-before-write)."""
    p = _plan
    if p is None:
        for c in chunks:
            fh.write(c)
        return
    r = p.visit(name, ctx)
    if r is None:
        for c in chunks:
            fh.write(c)
        return
    if r.action == "torn":
        data = b"".join(chunks)
        cut = r.cut_len(len(data))
        fh.write(data[:cut])
        fh.flush()
        raise FaultCrash(
            f"faultline: torn write at {name} "
            f"({cut}/{len(data)} bytes), then crash"
        )
    r.execute()
    for c in chunks:
        fh.write(c)


class _FaultSocket:
    """Socket proxy visiting ``<name>.read`` / ``<name>.write`` fault
    points around recv/send.  A ``partial`` read returns a truncated
    chunk and marks the connection dead (the next read resets); a
    ``partial``/``torn`` write sends a prefix then resets.  Everything
    else passes through untouched."""

    def __init__(self, inner, name: str):
        self._fl_inner = inner
        self._fl_name = name
        self._fl_dead = False

    def __getattr__(self, attr):
        return getattr(self._fl_inner, attr)

    def _fl_visit(self, kind: str):
        if self._fl_dead:
            raise ConnectionResetError(
                f"faultline: {self._fl_name} connection reset (injected)"
            )
        p = _plan
        if p is None:
            return None
        return p.visit(f"{self._fl_name}.{kind}", {})

    def recv(self, bufsize: int, *args):
        r = self._fl_visit("read")
        if r is not None:
            if r.action == "partial":
                data = self._fl_inner.recv(bufsize, *args)
                self._fl_dead = True
                return data[: r.cut_len(len(data))]
            r.execute()
        return self._fl_inner.recv(bufsize, *args)

    def _fl_send(self, data, send_fn):
        r = self._fl_visit("write")
        if r is not None:
            if r.action in ("partial", "torn"):
                cut = r.cut_len(len(data))
                if cut:
                    self._fl_inner.sendall(data[:cut])
                self._fl_dead = True
                raise ConnectionResetError(
                    f"faultline: {self._fl_name} write torn at "
                    f"{cut}/{len(data)} bytes (injected)"
                )
            r.execute()
        return send_fn(data)

    def sendall(self, data):
        return self._fl_send(data, self._fl_inner.sendall)

    def send(self, data):
        return self._fl_send(data, self._fl_inner.send)


def io(sock, name: str):
    """Wrap a socket in read/write fault points ``<name>.read`` /
    ``<name>.write``.  Returns the socket UNCHANGED when no plan is
    armed — the wrapper only ever exists inside a chaos run."""
    if _plan is None:
        return sock
    return _FaultSocket(sock, name)


def is_crash(exc: BaseException) -> bool:
    """True for the simulated-process-death exception — cleanup/rollback
    seams skip their unwind for it so reopen exercises real recovery."""
    return isinstance(exc, FaultCrash)


# -- plan lifecycle -----------------------------------------------------------


def active() -> bool:
    return _plan is not None


def current_plan():
    return _plan


def lookup_count() -> int:
    """Total plan consultations so far — provably 0 while no plan has
    ever been armed (the zero-overhead acceptance probe)."""
    return _lookups[0]


def trips() -> list[dict]:
    """Snapshot of the process-wide trip ledger."""
    with _trips_lock:
        return [dict(t) for t in _trips]


def reset_trips() -> None:
    with _trips_lock:
        _trips.clear()


def activate(plan) -> Plan:
    """Arm a plan (dict, JSON string, or Plan).  Replaces any armed
    plan; trigger state starts fresh."""
    p = plan if isinstance(plan, Plan) else Plan(plan)
    global _plan
    with _state_lock:
        _plan = p
    return p


def deactivate() -> None:
    global _plan
    with _state_lock:
        _plan = None


@contextlib.contextmanager
def use_plan(plan):
    """Arm a plan for a scope and DRAIN on exit: the plan is disarmed
    and the trip ledger cleared, so the conftest session gate (which
    asserts no armed plan and an empty ledger) stays green for every
    test that keeps its chaos inside this context."""
    p = activate(plan)
    try:
        yield p
    finally:
        deactivate()
        reset_trips()


def _init_from_env() -> None:
    raw = os.environ.get(_ENV, "")
    if not raw or raw in ("0", "false", "off"):
        return
    if raw.startswith("@"):
        with open(raw[1:], "r", encoding="utf-8") as f:
            raw = f.read()
    activate(raw)


_init_from_env()


__all__ = [
    "PlanError",
    "FaultInjected",
    "FaultCrash",
    "DeviceUnavailable",
    "Plan",
    "point",
    "write",
    "io",
    "is_crash",
    "active",
    "current_plan",
    "lookup_count",
    "trips",
    "reset_trips",
    "activate",
    "deactivate",
    "use_plan",
]
