"""Batched idemix Schnorr recomputation on the device (BN254 G1).

The idemix verify hot path (reference idemix/signature.go:243 Ver)
re-derives three ZK commitments per signature — small G1 multi-scalar
multiplications — before the two pairings.  Round 2 ran this on the
native CPU backend; here the whole batch's MSMs execute as ONE jitted
XLA program over the shared limb machinery (csp/tpu/limbs.py, the same
16-bit-limb arithmetic the ECDSA kernel uses), with the pairings staying
on the native host path (csp's verify_batch collapses them to two per
batch via random linear combination).

Per signature the verifier needs (signature.py _relations +
schnorr.recompute_commitments, with targets flattened into the MSMs —
y1^(−c) = a_bar^(−c)·b_prime^{c}, y2^(−c) = G1^{c}·Π h_attrs[i]^{c·m_i}):

  T1 = a_bar^{-c} · b_prime^{c} · a_prime^{z_neg_e} · h_rand^{z_r2}
  T2 = G1^{c} · h_sk^{z_sk} · h_rand^{z_s'} · Π_i h_attrs[i]^{s_i}
         · b_prime^{z_neg_r3}         s_i = c·m_i (disclosed) | z_mi (hidden)
  T3 = nym^{-c} · h_sk^{z_sk} · h_rand^{z_r_nym}

Shared bases (G1, h_sk, h_rand, h_attrs[*]) come as precomputed affine
4-bit window tables (per issuer key, built once on host); per-lane bases
(a_prime, a_bar, b_prime, nym) get device-built Jacobian tables.  One
MSB-first 64-window ladder accumulates all three commitments; outputs
are Jacobian, normalized on host with one batched inversion.
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp

from fabric_tpu.csp.tpu import ec, limbs
from fabric_tpu.csp.tpu.ec import Aff, Jac
from fabric_tpu.csp.tpu.limbs import WIDE
from fabric_tpu.idemix import bn254 as bn

NWINDOWS = 64
TABLE = 16
# pad buckets (one XLA compile per (bucket, n_attrs)); batches beyond
# the largest bucket chunk at _MAX_LANES so compiled shapes are reused
_BUCKETS = (16, 64, 256, 1024)
_MAX_LANES = _BUCKETS[-1]

# per-lane scalar slots, fixed order (public: the Pallas engine keys
# its lane layout off this tuple)
LANE_BASES = ("a_prime", "a_bar", "b_prime", "nym")
_LANE_BASES = LANE_BASES  # backwards-compatible alias

# Pallas failure bookkeeping, scoped per (batch, n_attrs) SHAPE with a
# bounded retry budget: one transient failure (an OOM at an unusually
# large bucket, a tunnel hiccup) must not permanently downgrade every
# later batch to the ~3-4x slower XLA engine, while a shape that fails
# repeatedly stops re-packing + re-failing + re-warning each time.
_PALLAS_FAILURES: dict = {}
_PALLAS_MAX_FAILURES = 2


def _pallas_preferred(shape=None) -> bool:
    """Use the Pallas engine only where it runs compiled: on the TPU
    backend (or when a test forces it — interpret mode executes the
    grid in Python and would be far slower than the XLA fallback it
    preempts on CPU/GPU hosts)."""
    if os.environ.get("FABRIC_BN254_NO_PALLAS"):
        return False
    if _PALLAS_FAILURES.get(shape, 0) >= _PALLAS_MAX_FAILURES:
        return False
    if os.environ.get("FABRIC_BN254_FORCE_PALLAS"):
        return True
    return jax.default_backend() == "tpu"


def _fp():
    # Montgomery context: all device coordinates live in Montgomery form
    # x·R mod p (R = 2**272), where a 254-bit mul costs one REDC instead
    # of ~6 fold passes (limbs.MontMod) — conversion happens only in the
    # host int<->limb boundary helpers below
    return limbs.mont_ctx(bn.P)


def _to_limbs(x: int) -> np.ndarray:
    return limbs.int_to_limbs(_fp().to_mont_int(x), WIDE)


def _recode(u: int) -> np.ndarray:
    return np.asarray(
        [(u >> (4 * (NWINDOWS - 1 - k))) & 15 for k in range(NWINDOWS)],
        np.int32,
    )


@functools.lru_cache(maxsize=8)
def shared_multiples(ipk_key: tuple) -> tuple:
    """k*P for k in 0..15 per shared base (None = infinity): the raw
    host scalar multiplications both device engines derive their window
    tables from (one cache, not one per engine).  ipk_key is the
    hashable ((x, y), ...) tuple of (G1, h_sk, h_rand, *h_attrs)."""
    return tuple(
        tuple(bn.g1_mul(pt, k) if k else None for k in range(TABLE))
        for pt in ipk_key
    )


@functools.lru_cache(maxsize=8)
def shared_tables(ipk_key: tuple) -> dict:
    """Affine 4-bit window tables (16 multiples) for the issuer key's
    fixed bases in the XLA engine's limb layout."""
    tabs_x, tabs_y, tabs_inf = [], [], []
    for row in shared_multiples(ipk_key):
        xs, ys, infs = [], [], []
        for q in row:
            if q is None:
                xs.append(_to_limbs(0))
                ys.append(_to_limbs(0))
                infs.append(True)
            else:
                xs.append(_to_limbs(q[0]))
                ys.append(_to_limbs(q[1]))
                infs.append(False)
        tabs_x.append(np.stack(xs))
        tabs_y.append(np.stack(ys))
        tabs_inf.append(np.asarray(infs))
    return {
        "x": np.stack(tabs_x),  # (n_shared, 16, 17)
        "y": np.stack(tabs_y),
        "inf": np.stack(tabs_inf),  # (n_shared, 16)
    }


def _dbl_a0(fp, p: Jac) -> Jac:
    """Jacobian doubling for a = 0 (BN254: y^2 = x^3 + 3), dbl-2009-l."""
    a = fp.sqr(p.x)
    b = fp.sqr(p.y)
    c = fp.sqr(b)
    d_inner = fp.sqr(fp.add(p.x, b))
    d = fp.mul_const(fp.sub(fp.sub(d_inner, a), c), 2)
    e = fp.mul_const(a, 3)
    f = fp.sqr(e)
    x3 = fp.sub(f, fp.add(d, d))
    y3 = fp.sub(fp.mul(e, fp.sub(d, x3)), fp.mul_const(c, 8))
    z3 = fp.mul_const(fp.mul(p.y, p.z), 2)
    return Jac(x3, y3, z3, p.inf)


def _lane_window_table(fp, px, py, pinf):
    """Jacobian multiples 0..15 of per-lane affine points, a=0 chain."""
    b = px.shape[:-1]
    zero = jnp.zeros(b + (WIDE,), jnp.uint32)
    inf_t = jnp.ones(b, bool)
    p_aff = Aff(px, py, pinf)
    p1 = Jac(px, py, fp.one_like(px), pinf)

    def step(p: Jac, _):
        nxt = ec.point_add_mixed(fp, p, p_aff, dbl=_dbl_a0)
        return nxt, nxt

    _, rest = jax.lax.scan(step, p1, None, length=TABLE - 2)
    cat = lambda z, o, r: jnp.concatenate(  # noqa: E731
        [z[..., None, :], o[..., None, :], jnp.moveaxis(r, 0, -2)], axis=-2
    )
    tinf = jnp.concatenate(
        [inf_t[..., None], pinf[..., None], jnp.moveaxis(rest.inf, 0, -1)],
        axis=-1,
    )
    return (
        cat(zero, p1.x, rest.x),
        cat(zero, p1.y, rest.y),
        cat(zero, p1.z, rest.z),
        tinf,
    )


def commitments_kernel(
    lane_x, lane_y, lane_inf,      # (4, B, 17) / (4, B)  a',abar,b',nym
    shared_x, shared_y, shared_inf,  # (n_shared, 16, 17) / (n_shared, 16)
    digits,                        # (n_terms, B, 64) int32
    term_table,                    # (n_terms,) int32: unified table index
    term_acc,                      # (n_terms,) int32: accumulator 0..2
):
    """One joint 64-window MSB-first ladder accumulating T1, T2, T3.

    Kept deliberately SMALL as a traced graph: the three accumulators
    are one stacked (3, B) Jacobian (one vectorized doubling), all
    window tables live in one (n_tables, B, 16) stack, and the per-term
    adds run as an inner scan whose body is a single full Jacobian add
    with dynamic table/accumulator indexing.  (An unrolled-terms
    variant with static table slices and mixed affine adds was measured
    SLOWER on the chip — 2.19s vs 1.46s at 1024 lanes — and tripled
    compile time; the scan structure is what lets XLA keep the working
    set resident, so it stays.)"""
    fp = _fp()
    b = lane_x.shape[1]
    n_shared = shared_x.shape[0]

    # per-lane Jacobian tables, all 4 bases at once (batch dims (4, B))
    ltx, lty, ltz, ltinf = _lane_window_table(fp, lane_x, lane_y, lane_inf)
    # unified stack: shared tables broadcast over lanes, z = 1, then the
    # 4 per-lane tables.  (n_tables, B, 16, 17) / (n_tables, B, 16)
    ones = jnp.broadcast_to(
        fp.one_like(shared_x)[:, None], (n_shared, b, TABLE, WIDE)
    )
    utx = jnp.concatenate(
        [jnp.broadcast_to(shared_x[:, None], (n_shared, b, TABLE, WIDE)),
         ltx], axis=0
    )
    uty = jnp.concatenate(
        [jnp.broadcast_to(shared_y[:, None], (n_shared, b, TABLE, WIDE)),
         lty], axis=0
    )
    utz = jnp.concatenate([ones, ltz], axis=0)
    utinf = jnp.concatenate(
        [jnp.broadcast_to(shared_inf[:, None], (n_shared, b, TABLE)),
         ltinf], axis=0
    )

    zeros = jnp.zeros((3, b, WIDE), jnp.uint32)
    acc0 = Jac(zeros, zeros, zeros, jnp.ones((3, b), bool))

    def window(acc, w):
        for _ in range(4):
            acc = _dbl_a0(fp, acc)  # all 3 accumulators at once

        def term(acc, t):
            dig = jax.lax.dynamic_index_in_dim(
                digits, t, axis=0, keepdims=False
            )[:, w]  # (B,)
            ti = term_table[t]
            gx = jax.lax.dynamic_index_in_dim(utx, ti, 0, keepdims=False)
            gy = jax.lax.dynamic_index_in_dim(uty, ti, 0, keepdims=False)
            gz = jax.lax.dynamic_index_in_dim(utz, ti, 0, keepdims=False)
            ginf = jax.lax.dynamic_index_in_dim(
                utinf, ti, 0, keepdims=False
            )
            q = ec._gather_pt(gx, gy, gz, ginf, dig)
            ai = term_acc[t]
            cur = Jac(
                jax.lax.dynamic_index_in_dim(acc.x, ai, 0, False),
                jax.lax.dynamic_index_in_dim(acc.y, ai, 0, False),
                jax.lax.dynamic_index_in_dim(acc.z, ai, 0, False),
                jax.lax.dynamic_index_in_dim(acc.inf, ai, 0, False),
            )
            new = ec.point_add(fp, cur, q, dbl=_dbl_a0)
            upd = lambda s, v: jax.lax.dynamic_update_index_in_dim(  # noqa: E731
                s, v, ai, 0
            )
            return Jac(
                upd(acc.x, new.x), upd(acc.y, new.y),
                upd(acc.z, new.z), upd(acc.inf, new.inf),
            ), None

        acc, _ = jax.lax.scan(term, acc, jnp.arange(digits.shape[0]))
        return acc, None

    acc, _ = jax.lax.scan(window, acc0, jnp.arange(NWINDOWS))
    return (
        fp.canon(acc.x), fp.canon(acc.y), fp.canon(acc.z),
        acc.inf.astype(jnp.uint32),
    )


@functools.lru_cache(maxsize=None)
def _jit_kernel():
    return jax.jit(commitments_kernel)


def schnorr_commitments_batch(sigs, ipk) -> list | None:
    """Device-batched T1/T2/T3 for every signature; returns per-sig
    [(T1, T2, T3)] as affine int tuples (None = infinity), or None for
    lanes whose inputs are malformed (caller marks them failed).

    Mirrors signature._relations + schnorr.recompute_commitments; parity
    is enforced by tests/test_bn254_device.py against the host path.
    """
    n = len(sigs)
    if n == 0:
        return []
    if n > _MAX_LANES:
        # chunk at the largest bucket: bounds pad waste to the tail and
        # reuses the already-compiled shapes
        out: list = []
        for off in range(0, n, _MAX_LANES):
            out.extend(
                schnorr_commitments_batch(sigs[off:off + _MAX_LANES], ipk)
            )
        return out
    n_attrs = len(ipk.h_attrs)
    shared_pts = (bn.G1_GEN, ipk.h_sk, ipk.h_rand, *ipk.h_attrs)
    n_shared = len(shared_pts)
    # unified term layout: (table index, accumulator).  Shared tables
    # occupy indices 0..n_shared-1 of the kernel's table stack, the 4
    # per-lane bases (_LANE_BASES order) follow at n_shared+0..3.
    #   T1: h_rand^z_r2, a_bar^{-c}, b_prime^{c}, a_prime^{z_neg_e}
    #   T2: G1^c, h_sk^z_sk, h_rand^z_s', h_attrs[i]^{s_i}, b'^{z_neg_r3}
    #   T3: h_sk^z_sk, h_rand^z_r_nym, nym^{-c}
    term_table = (
        2, n_shared + 1, n_shared + 2, n_shared + 0,
        0, 1, 2, *range(3, 3 + n_attrs), n_shared + 2,
        1, 2, n_shared + 3,
    )
    term_acc = (0, 0, 0, 0, 1, 1, 1, *([1] * n_attrs), 1, 2, 2, 2)

    pts_l, scalars_l, ok = _prepare_sigs(sigs, ipk, n_attrs)

    # preferred engine: the fused Pallas ladder (VMEM-resident Montgomery
    # field ops, pallas_bn254.py); the XLA scan kernel is the fallback
    # when Mosaic is unavailable or fails
    jac = None
    # budget key = the COMPILE bucket, not the raw batch length: every
    # length padding to the same bucket shares one compiled kernel, so
    # a deterministic failure is retried per compile unit, not per
    # distinct batch size
    bucket = next((b for b in _BUCKETS if len(ok) <= b), _MAX_LANES)
    shape = (bucket, n_attrs)
    if _pallas_preferred(shape):
        try:
            from fabric_tpu.csp.tpu import pallas_bn254

            jac = pallas_bn254.commitments(
                pts_l, scalars_l, ok, term_table, term_acc, shared_pts
            )
            _PALLAS_FAILURES.pop(shape, None)  # success resets the budget
        except Exception as exc:
            from fabric_tpu.common.flogging import must_get_logger

            _PALLAS_FAILURES[shape] = _PALLAS_FAILURES.get(shape, 0) + 1
            must_get_logger("bn254").warning(
                "pallas BN254 ladder failed for shape %s (%s: %s), "
                "failure %d/%d; using the XLA path for this batch",
                shape, type(exc).__name__, exc,
                _PALLAS_FAILURES[shape], _PALLAS_MAX_FAILURES,
            )
            jac = None
    if jac is None:
        jac = _commitments_xla(
            pts_l, scalars_l, ok, term_table, term_acc, shared_pts
        )

    # Jacobian -> affine with ONE batched modular inversion (host ints)
    zs, metas = [], []
    results: list = [None] * n
    for j in range(n):
        if not ok[j]:
            continue
        tri = jac[j]
        metas.append((j, tri))
        for (_, _, zv, inf) in tri:
            zs.append(1 if (inf or zv == 0) else zv)
    if metas:
        invs = _batch_inverse(zs, bn.P)
        k = 0
        for j, tri in metas:
            pts = []
            for (x, y, zv, inf) in tri:
                if inf or zv == 0:
                    pts.append(None)
                else:
                    zi = invs[k]
                    zi2 = zi * zi % bn.P
                    pts.append((x * zi2 % bn.P, y * zi2 * zi % bn.P))
                k += 1
            results[j] = tuple(pts)
    return results


def _prepare_sigs(sigs, ipk, n_attrs):
    """Shared host prep for both device engines: per sig the 4 lane
    base points, the n_terms scalars (term order matching term_table),
    and validity.  Bad sigs get ok=False (the engines run them with
    zero scalars / infinity bases and the caller marks them failed)."""
    pts_l: list = []
    scalars_l: list = []
    ok = [True] * len(sigs)
    for j, sig in enumerate(sigs):
        try:
            pts = (sig.a_prime, sig.a_bar, sig.b_prime, sig.nym)
            if any(p is None or not bn.g1_is_on_curve(p) for p in pts):
                raise ValueError("bad point")
            if len(sig.disclosure) != n_attrs:
                raise ValueError("bad disclosure length")
            c = sig.challenge % bn.R
            z = sig.responses
            hidden = [i for i, d in enumerate(sig.disclosure) if not d]
            need = {"neg_e", "r2", "sk", "sprime", "neg_r3", "r_nym",
                    *{f"m_{i}" for i in hidden}}
            if not need <= set(z):
                raise ValueError("missing responses")
            s_attr = []
            for i in range(n_attrs):
                if sig.disclosure[i]:
                    if i not in sig.disclosed_attrs:
                        raise ValueError("missing disclosed attr")
                    s_attr.append((c * sig.disclosed_attrs[i]) % bn.R)
                else:
                    s_attr.append(z[f"m_{i}"] % bn.R)
            scalars = [
                # T1
                z["r2"] % bn.R,         # h_rand
                (-c) % bn.R,            # a_bar
                c,                      # b_prime
                z["neg_e"] % bn.R,      # a_prime
                # T2
                c,                      # G1
                z["sk"] % bn.R,         # h_sk
                z["sprime"] % bn.R,     # h_rand
                *s_attr,                # h_attrs
                z["neg_r3"] % bn.R,     # b_prime
                # T3
                z["sk"] % bn.R,         # h_sk
                z["r_nym"] % bn.R,      # h_rand
                (-c) % bn.R,            # nym
            ]
            pts_l.append(pts)
            scalars_l.append(scalars)
        except (ValueError, IndexError, KeyError, TypeError,
                OverflowError, AttributeError):
            ok[j] = False  # zero scalars: lane computes but is ignored
            pts_l.append((None,) * 4)
            scalars_l.append(None)
    return pts_l, scalars_l, ok


def _commitments_xla(pts_l, scalars_l, ok, term_table, term_acc,
                     shared_pts):
    """The XLA scan-kernel engine: returns per-sig [(x, y, z, inf)] * 3
    Jacobian ints in plain (non-Montgomery) form."""
    n = len(pts_l)
    n_terms = len(term_table)
    tabs = shared_tables(tuple(shared_pts))

    lane_x = np.zeros((4, n, WIDE), np.uint32)
    lane_y = np.zeros((4, n, WIDE), np.uint32)
    lane_inf = np.zeros((4, n), bool)
    digits = np.zeros((n_terms, n, NWINDOWS), np.int32)
    for j in range(n):
        if not ok[j]:
            lane_inf[:, j] = True
            continue
        for i, p in enumerate(pts_l[j]):
            lane_x[i, j] = _to_limbs(p[0])
            lane_y[i, j] = _to_limbs(p[1])
        for t, u in enumerate(scalars_l[j]):
            digits[t, j] = _recode(u)

    # pad lanes to a bucket size so each (bucket, n_attrs) pair compiles
    # once; padded lanes carry zero scalars (every digit selects the
    # infinity table entry) and are sliced away below
    bsz = _BUCKETS[0]
    for b in _BUCKETS:
        if n <= b:
            bsz = b
            break
    if bsz != n:
        pad = bsz - n
        lane_x = np.concatenate(
            [lane_x, np.zeros((4, pad, WIDE), np.uint32)], axis=1
        )
        lane_y = np.concatenate(
            [lane_y, np.zeros((4, pad, WIDE), np.uint32)], axis=1
        )
        lane_inf = np.concatenate(
            [lane_inf, np.ones((4, pad), bool)], axis=1
        )
        digits = np.concatenate(
            [digits, np.zeros((n_terms, pad, NWINDOWS), np.int32)], axis=1
        )
    kern = _jit_kernel()
    ax, ay, az, ainf = kern(
        jnp.asarray(lane_x), jnp.asarray(lane_y), jnp.asarray(lane_inf),
        jnp.asarray(tabs["x"]), jnp.asarray(tabs["y"]),
        jnp.asarray(tabs["inf"]),
        jnp.asarray(digits),
        jnp.asarray(term_table, jnp.int32),
        jnp.asarray(term_acc, jnp.int32),
    )
    ax, ay, az, ainf = (np.asarray(o) for o in (ax, ay, az, ainf))
    fp = _fp()
    jac = []
    for j in range(n):
        if not ok[j]:
            jac.append(None)
            continue
        tri = []
        for t in range(3):
            x = fp.from_mont_int(limbs.limbs_to_int(ax[t, j]))
            y = fp.from_mont_int(limbs.limbs_to_int(ay[t, j]))
            zv = fp.from_mont_int(limbs.limbs_to_int(az[t, j]))
            inf = bool(ainf[t, j])
            tri.append((x, y, zv, inf))
        jac.append(tri)
    return jac


def _batch_inverse(vals: list[int], m: int) -> list[int]:
    """Montgomery's trick: one pow for the whole list."""
    pre = [1] * (len(vals) + 1)
    for i, v in enumerate(vals):
        pre[i + 1] = pre[i] * v % m
    inv = pow(pre[-1], -1, m)
    out = [0] * len(vals)
    for i in range(len(vals) - 1, -1, -1):
        out[i] = inv * pre[i] % m
        inv = inv * vals[i] % m
    return out


__all__ = ["schnorr_commitments_batch", "shared_tables"]
