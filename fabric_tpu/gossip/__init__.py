from fabric_tpu.gossip.comm import (
    GossipComm,
    InProcGossipComm,
    InProcGossipNet,
    MessageCryptoService,
    SignerMCS,
    TCPGossipComm,
)
from fabric_tpu.gossip.core import ChannelGossip, MessageStore
from fabric_tpu.gossip.discovery import Discovery, DiscoveryCore
from fabric_tpu.gossip.election import LeaderElection
from fabric_tpu.gossip.service import GossipRunner, GossipService
from fabric_tpu.gossip.state import StateProvider

__all__ = [
    "GossipComm",
    "InProcGossipComm",
    "InProcGossipNet",
    "TCPGossipComm",
    "MessageCryptoService",
    "SignerMCS",
    "ChannelGossip",
    "MessageStore",
    "Discovery",
    "DiscoveryCore",
    "LeaderElection",
    "GossipService",
    "GossipRunner",
    "StateProvider",
]
