"""Envelope/header helpers (reference protoutil/commonutils.go:23-60,
proputils.go:368 CheckTxID)."""

from __future__ import annotations

import dataclasses
import os
import time
import typing

from fabric_tpu.common.hashing import sha256 as _sha256
from fabric_tpu.protos.common import common_pb2


class SignedData(typing.NamedTuple):
    """A (message, identity, signature) triple — the unit fed to policy
    evaluation and batch verification (reference protoutil/signeddata.go).

    `digest`, when set, is the precomputed SHA-256 of `data` (the native
    block-collect pass hashes while walking the wire format); verifiers
    use it instead of re-hashing.  `data` may then be b"" — nothing
    downstream of policy prepare reads it.

    A NamedTuple (hot-path churn: the validator creates one per
    endorsement lane, thousands per block — tuple construction runs in
    C at roughly half the dataclass __init__ cost)."""

    data: bytes
    identity: bytes  # marshaled msp.SerializedIdentity
    signature: bytes
    digest: bytes | None = None


def random_nonce(n: int = 24) -> bytes:
    """CSPRNG nonce (reference common/crypto/random.go: 24-byte nonces)."""
    return os.urandom(n)


def compute_tx_id(nonce: bytes, creator: bytes) -> str:
    """TxID = hex(SHA-256(nonce || creator)) — the binding the reference
    enforces in protoutil CheckTxID."""
    return _sha256(nonce + creator).hex()


def check_tx_id(txid: str, nonce: bytes, creator: bytes) -> bool:
    return txid == compute_tx_id(nonce, creator)


def make_channel_header(
    header_type: int,
    channel_id: str,
    tx_id: str = "",
    epoch: int = 0,
    extension: bytes = b"",
    version: int = 0,
    timestamp: float | None = None,
) -> common_pb2.ChannelHeader:
    ch = common_pb2.ChannelHeader(
        type=header_type,
        version=version,
        channel_id=channel_id,
        tx_id=tx_id,
        epoch=epoch,
        extension=extension,
    )
    # fabriclint: allow[determinism] client-side tx-authoring timestamp;
    # validators never recompute or compare it against their own clocks
    ts = time.time() if timestamp is None else timestamp
    ch.timestamp.seconds = int(ts)
    return ch


def make_signature_header(creator: bytes, nonce: bytes) -> common_pb2.SignatureHeader:
    return common_pb2.SignatureHeader(creator=creator, nonce=nonce)


def make_payload_bytes(
    channel_header: common_pb2.ChannelHeader,
    signature_header: common_pb2.SignatureHeader,
    data: bytes,
) -> bytes:
    return common_pb2.Payload(
        header=common_pb2.Header(
            channel_header=channel_header.SerializeToString(),
            signature_header=signature_header.SerializeToString(),
        ),
        data=data,
    ).SerializeToString()


def make_envelope(payload_bytes: bytes, signer=None) -> common_pb2.Envelope:
    """Wrap payload bytes; `signer` (optional) has .sign(msg) -> bytes."""
    sig = signer.sign(payload_bytes) if signer is not None else b""
    return common_pb2.Envelope(payload=payload_bytes, signature=sig)


def unmarshal_envelope(raw: bytes) -> common_pb2.Envelope:
    return common_pb2.Envelope.FromString(raw)


def unmarshal_payload(raw: bytes) -> common_pb2.Payload:
    return common_pb2.Payload.FromString(raw)


def unmarshal_channel_header(raw: bytes) -> common_pb2.ChannelHeader:
    return common_pb2.ChannelHeader.FromString(raw)


def unmarshal_signature_header(raw: bytes) -> common_pb2.SignatureHeader:
    return common_pb2.SignatureHeader.FromString(raw)


def channel_header(env: common_pb2.Envelope) -> common_pb2.ChannelHeader:
    """Extract the ChannelHeader from an Envelope (reference
    protoutil/commonutils.go ChannelHeader)."""
    payload = common_pb2.Payload.FromString(env.payload)
    return common_pb2.ChannelHeader.FromString(payload.header.channel_header)
