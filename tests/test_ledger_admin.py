"""Ledger repair ops (peer node rebuild-dbs / rollback / reset), rich
JSON-selector queries, filtered-block deliver, and the caching MSP."""

import json

import pytest

from fabric_tpu.ledger import LedgerProvider
from fabric_tpu.ledger import admin
from fabric_tpu.ledger.richquery import execute_query, match_selector


# -- rich queries ----------------------------------------------------------


class TestRichQuery:
    def test_selectors(self):
        doc = {"color": "red", "size": 5, "owner": {"org": "Org1"}}
        assert match_selector(doc, {"color": "red"})
        assert not match_selector(doc, {"color": "blue"})
        assert match_selector(doc, {"size": {"$gt": 3, "$lte": 5}})
        assert match_selector(doc, {"owner.org": "Org1"})
        assert match_selector(doc, {"color": {"$in": ["red", "blue"]}})
        assert match_selector(doc, {"weight": {"$exists": False}})
        assert not match_selector(doc, {"size": {"$ne": 5}})
        assert match_selector(
            doc, {"$or": [{"color": "blue"}, {"size": {"$gte": 5}}]}
        )

    def test_execute_query_scan(self):
        pairs = [
            ("a1", json.dumps({"t": "car", "price": 10}).encode()),
            ("a2", json.dumps({"t": "car", "price": 30}).encode()),
            ("a3", json.dumps({"t": "boat", "price": 30}).encode()),
            ("a4", b"not-json"),
        ]
        q = json.dumps({"selector": {"t": "car", "price": {"$gt": 5}}})
        assert [k for k, _ in execute_query(pairs, q)] == ["a1", "a2"]
        q = json.dumps({"selector": {"price": {"$gte": 10}}, "limit": 2})
        assert len(execute_query(pairs, q)) == 2

    def test_simulator_get_query_result(self):
        from fabric_tpu.ledger.kvstore import MemKVStore
        from fabric_tpu.ledger.statedb import Height, VersionedDB, VersionedValue
        from fabric_tpu.ledger.txmgmt import TxSimulator

        db = VersionedDB(MemKVStore())
        db.apply_updates(
            {
                "cc": {
                    "m1": VersionedValue(
                        json.dumps({"make": "tesla"}).encode(), Height(1, 0)
                    ),
                    "m2": VersionedValue(
                        json.dumps({"make": "ford"}).encode(), Height(1, 1)
                    ),
                }
            },
            Height(1, 2),
        )
        sim = TxSimulator(db)
        rows = sim.get_query_result(
            "cc", json.dumps({"selector": {"make": "tesla"}})
        )
        assert [k for k, _ in rows] == ["m1"]


# -- repair ops ------------------------------------------------------------


def _make_chain(tmp_path, n_blocks=3):
    """A committed chain via the devnode-free path: genesis + n blocks."""
    from orgfix import make_org
    from fabric_tpu.common import configtx_builder as ctx
    from fabric_tpu.msp import msp_config_from_ca
    from fabric_tpu.node.devnode import DevNode

    org = make_org("Org1MSP")
    oorg = make_org("OrdererMSP")
    app = ctx.application_group(
        {"Org1": ctx.org_group("Org1MSP", msp_config_from_ca(org.ca, "Org1MSP"))}
    )
    ordg = ctx.orderer_group(
        {"O": ctx.org_group("OrdererMSP", msp_config_from_ca(oorg.ca, "OrdererMSP"))},
        consensus_type="solo",
        max_message_count=1,
    )
    genesis = ctx.genesis_block("repairch", ctx.channel_group(app, ordg))
    peer = org.signer("peer0", role_ou="peer")
    client = org.signer("user", role_ou="client")

    def kv(sim, args):
        sim.set_state("kv", args[0].decode(), args[1])
        return 200, "", b""

    node = DevNode(
        genesis, root_dir=str(tmp_path), csp=org.csp, peer_signer=peer,
        chaincodes={"kv": kv}, batch_timeout_s=0.05,
    )
    from fabric_tpu import protoutil
    from fabric_tpu.protos.peer import proposal_pb2

    for i in range(n_blocks):
        prop, _ = protoutil.create_chaincode_proposal(
            client.serialize(), "repairch", "kv",
            [b"k%d" % i, b"v%d" % i],
        )
        signed = proposal_pb2.SignedProposal(
            proposal_bytes=prop.SerializeToString(),
            signature=client.sign(prop.SerializeToString()),
        )
        resp = node.endorser.process_proposal(signed)
        env = protoutil.create_signed_tx(prop, client, [resp])
        node.broadcast(env)
        node.wait_commit()
    node.shutdown()
    node.provider.close()
    return "repairch"


def test_rebuild_dbs_replays_state(tmp_path):
    lid = _make_chain(tmp_path, 3)
    assert admin.rebuild_dbs(str(tmp_path)) == [lid]
    provider = LedgerProvider(str(tmp_path))
    ledger = provider.open(lid)
    assert ledger.height == 4
    assert ledger.get_state("kv", "k2") == b"v2"
    provider.close()


def test_rollback_truncates_and_replays(tmp_path):
    lid = _make_chain(tmp_path, 3)
    assert admin.rollback(str(tmp_path), lid, 2) == 3
    provider = LedgerProvider(str(tmp_path))
    ledger = provider.open(lid)
    assert ledger.height == 3
    assert ledger.get_state("kv", "k1") == b"v1"
    assert ledger.get_state("kv", "k2") is None  # rolled off
    provider.close()


def test_reset_to_genesis(tmp_path):
    lid = _make_chain(tmp_path, 2)
    assert admin.reset(str(tmp_path)) == {lid: 1}
    provider = LedgerProvider(str(tmp_path))
    ledger = provider.open(lid)
    assert ledger.height == 1
    assert ledger.get_state("kv", "k0") is None
    provider.close()


# -- filtered blocks -------------------------------------------------------


def test_filter_block(tmp_path):
    lid = _make_chain(tmp_path, 1)
    provider = LedgerProvider(str(tmp_path))
    ledger = provider.open(lid)
    blk = ledger.get_block_by_number(1)
    from fabric_tpu.common.deliver import filter_block
    from fabric_tpu.protos.peer import transaction_pb2 as V

    fb = filter_block(blk)
    assert fb.number == 1 and fb.channel_id == "repairch"
    assert len(fb.filtered_transactions) == 1
    ftx = fb.filtered_transactions[0]
    assert ftx.txid and ftx.tx_validation_code == V.VALID
    # no payloads/rwsets travel in a filtered block
    assert len(fb.SerializeToString()) < len(blk.SerializeToString()) / 4
    provider.close()


# -- MSP cache -------------------------------------------------------------

def test_cached_msp_memoizes():
    from orgfix import make_org
    from fabric_tpu.msp.cache import CachedMSP

    org = make_org("Org1MSP")
    signer = org.signer("peer0")
    raw = signer.serialize()

    calls = {"de": 0, "val": 0}

    class Spy:
        def deserialize_identity(self, s):
            calls["de"] += 1
            return org.msp.deserialize_identity(s)

        def validate(self, ident):
            calls["val"] += 1
            return org.msp.validate(ident)

    cached = CachedMSP(Spy())
    i1 = cached.deserialize_identity(raw)
    i2 = cached.deserialize_identity(raw)
    assert calls["de"] == 1 and i1 is i2
    cached.validate(i1)
    cached.validate(i2)
    assert calls["val"] == 1


def test_pause_resume_and_upgrade_dbs(tmp_path):
    """pause/resume markers + data-format stamp (reference
    internal/peer/node/{pause,resume,upgrade_dbs}.go)."""
    from fabric_tpu.ledger import admin

    root = str(tmp_path / "peer")
    import os

    os.makedirs(root)
    # seed a dummy index store via pause itself
    admin.pause(root, "ch1")
    admin.pause(root, "ch2")
    assert admin.paused_channels(root) == {"ch1", "ch2"}
    admin.resume(root, "ch1")
    assert admin.paused_channels(root) == {"ch2"}
    # upgrade stamps the format; second run is a no-op
    admin.upgrade_dbs(root)
    assert admin.upgrade_dbs(root) == []


def _index_db(docs):
    from fabric_tpu.ledger.kvstore import MemKVStore
    from fabric_tpu.ledger.statedb import Height, VersionedDB, VersionedValue

    db = VersionedDB(MemKVStore())
    db.apply_updates(
        {
            "cc": {
                k: VersionedValue(json.dumps(d).encode(), Height(1, i))
                for i, (k, d) in enumerate(docs.items())
            }
        },
        Height(1, len(docs)),
    )
    return db


def _scan_and_indexed(db, selector, **extra):
    from fabric_tpu.ledger.richquery import execute_query_indexed

    q = json.dumps({"selector": selector, **extra})
    scan = [
        k
        for k, _ in execute_query(
            ((k, vv.value) for k, vv in db.get_state_range("cc", "", "")), q
        )
    ]
    indexed = execute_query_indexed(db, "cc", q)
    return scan, indexed


class TestIndexedQueryParity:
    """Indexed execution must never under-select vs the full scan
    (advisor round-2 high finding): non-scalar operands and bool/number
    cross-type matches (True == 1 under Python ==, different index type
    tags) have to fall back or probe both encodings."""

    def _db(self, docs):
        return _index_db(docs)

    def _both(self, db, selector, **extra):
        return _scan_and_indexed(db, selector, **extra)

    def test_nonscalar_eq_falls_back_to_scan(self):
        db = self._db({"d1": {"tags": ["a", "b"]}, "d2": {"tags": "x"}})
        db.define_index("cc", "tags")
        scan, indexed = self._both(db, {"tags": ["a", "b"]})
        assert scan == ["d1"]
        assert indexed is None  # planner must decline, not return []

    def test_bool_number_cross_type_eq(self):
        db = self._db(
            {"b1": {"flag": True}, "n1": {"flag": 1}, "z": {"flag": 0},
             "b0": {"flag": False}, "n2": {"flag": 2}}
        )
        db.define_index("cc", "flag")
        for sel, want in [
            ({"flag": 1}, ["b1", "n1"]),      # 1 == True
            ({"flag": True}, ["b1", "n1"]),
            ({"flag": 0}, ["b0", "z"]),
            ({"flag": False}, ["b0", "z"]),
            ({"flag": 2}, ["n2"]),
            ({"flag": {"$in": [True, 2]}}, ["b1", "n1", "n2"]),
        ]:
            scan, indexed = self._both(db, sel)
            assert scan == want
            assert indexed is not None and [k for k, _, _ in indexed] == want

    def test_numeric_range_includes_bool_docs(self):
        db = self._db(
            {"b1": {"v": True}, "n1": {"v": 5}, "n0": {"v": -3}}
        )
        db.define_index("cc", "v")
        scan, indexed = self._both(db, {"v": {"$gte": 0}})
        assert scan == ["b1", "n1"]
        assert indexed is not None and [k for k, _, _ in indexed] == scan

    def test_bool_range_bound_falls_back(self):
        db = self._db({"n1": {"v": 5}, "b1": {"v": True}})
        db.define_index("cc", "v")
        scan, indexed = self._both(db, {"v": {"$gte": True}})
        assert indexed is None or [k for k, _, _ in indexed] == scan

    def test_unencodable_in_member_falls_back(self):
        db = self._db({"d1": {"v": [1, 2]}, "d2": {"v": "s"}})
        db.define_index("cc", "v")
        scan, indexed = self._both(db, {"v": {"$in": [[1, 2], "s"]}})
        assert scan == ["d1", "d2"]
        assert indexed is None

    def test_negative_zero_eq_and_range(self):
        db = self._db({"neg0": {"v": -0.0}, "pos0": {"v": 0}})
        db.define_index("cc", "v")
        for sel in ({"v": 0}, {"v": {"$gte": 0}}, {"v": {"$gte": -1, "$lte": 1}}):
            scan, indexed = self._both(db, sel)
            assert scan == ["neg0", "pos0"]
            assert indexed is not None and [k for k, _, _ in indexed] == scan

    def test_bool_sweep_gated_outside_01(self):
        db = self._db({"b1": {"v": True}, "n1": {"v": 500}})
        db.define_index("cc", "v")
        scan, indexed = self._both(db, {"v": {"$gte": 100}})
        assert scan == ["n1"]
        assert indexed is not None and [k for k, _, _ in indexed] == scan


class TestCompoundIndex:
    """Compound (multi-field) indexes: the planner rides only a FULLY
    eq-covered field set (optionally one trailing in/range on the last
    field); componentwise order must match tuple order; docs missing
    ANY indexed field never under-select because partial coverage is
    refused outright."""

    def _db(self, docs):
        return _index_db(docs)

    def _both(self, db, selector, **extra):
        return _scan_and_indexed(db, selector, **extra)

    DOCS = {
        "r1": {"color": "red", "size": 5, "w": 1},
        "r2": {"color": "red", "size": 9, "w": 2},
        "b1": {"color": "blue", "size": 5},
        "b2": {"color": "blue", "size": 7, "w": 9},
        "noc": {"size": 5},
        "nos": {"color": "red"},
        "arr": {"color": "red", "size": [5]},
        "nul": {"color": None, "size": 5},
    }

    def _cdb(self):
        db = self._db(self.DOCS)
        db.define_index("cc", ["color", "size"])
        return db

    def _check(self, db, selector, want_keys=None):
        scan, indexed = self._both(db, selector)
        assert indexed is not None, "compound plan declined unexpectedly"
        assert [k for k, _, _ in indexed] == scan
        if want_keys is not None:
            assert scan == want_keys

    def test_eq_eq(self):
        db = self._cdb()
        self._check(db, {"color": "red", "size": 5}, ["r1"])
        self._check(db, {"color": "blue", "size": 7}, ["b2"])
        self._check(db, {"color": None, "size": 5}, ["nul"])

    def test_partial_coverage_declines(self):
        # eq on the first field alone must NOT ride the compound index:
        # docs missing (or non-scalar in) the unconstrained field —
        # nos, arr — are absent from the index yet match the selector
        # (CouchDB's partial-index under-selection gotcha)
        db = self._cdb()
        scan, indexed = self._both(db, {"color": "red"})
        assert indexed is None
        assert scan == ["arr", "nos", "r1", "r2"]

    def test_eq_range(self):
        db = self._cdb()
        self._check(db, {"color": "red", "size": {"$gte": 6}}, ["r2"])
        self._check(
            db, {"color": "blue", "size": {"$gt": 1, "$lt": 8}},
            ["b1", "b2"],
        )
        self._check(db, {"color": "red", "size": {"$lte": 5}}, ["r1"])

    def test_eq_in(self):
        self._check(
            self._cdb(),
            {"color": "red", "size": {"$in": [5, 7]}},
            ["r1"],
        )

    def test_missing_field_docs_never_underselect(self):
        # noc/nos/arr are absent from the index; the planned conditions
        # require presence of scalars, so parity holds by construction
        db = self._cdb()
        self._check(db, {"color": "red", "size": 5}, ["r1"])
        scan, indexed = self._both(db, {"color": "red", "size": [5]})
        assert scan == ["arr"]
        assert indexed is None  # non-scalar operand: planner declines

    def test_string_order_edge_cases(self):
        # component order must equal tuple order even with prefixes and
        # embedded NULs in string values
        db = self._db({
            "a": {"f": "ab", "g": 1},
            "b": {"f": "abc", "g": 1},
            "c": {"f": "ab" + chr(0) + "x", "g": 1},
            "d": {"f": "ab", "g": 2},
        })
        db.define_index("cc", ["f", "g"])
        self._check(db, {"f": "ab", "g": 1}, ["a"])
        self._check(db, {"f": "ab" + chr(0) + "x", "g": 1}, ["c"])
        self._check(db, {"f": "abc", "g": {"$gte": 0}}, ["b"])
        self._check(db, {"f": "ab", "g": {"$gte": 1}}, ["a", "d"])

    def test_bool_number_cross_type_components(self):
        db = self._db({
            "t1": {"a": True, "b": 1},
            "n1": {"a": 1, "b": True},
            "x": {"a": 2, "b": 2},
        })
        db.define_index("cc", ["a", "b"])
        # True == 1 under python ==; both encodings must be probed on
        # BOTH components
        self._check(db, {"a": 1, "b": 1}, ["n1", "t1"])
        self._check(db, {"a": True, "b": True}, ["n1", "t1"])
        self._check(db, {"a": 2, "b": {"$gte": 0}}, ["x"])
        # bool doc value vs numeric trailing range sweeps the bool region
        self._check(db, {"a": 1, "b": {"$gte": 0}}, ["n1", "t1"])

    def test_longer_prefix_beats_shorter(self):
        from fabric_tpu.ledger.richquery import plan_index

        db = self._db({"d": {"x": 1, "y": 2, "z": 3}})
        db.define_index("cc", ["x"])
        db.define_index("cc", ["x", "y", "z"])
        p = plan_index(
            {"x": 1, "y": 2, "z": 3}, db.indexes_for("cc")
        )
        assert p[0] == "comp" and len(p[3]) == 3  # all three eqs ride

    def test_mutation_maintains_compound_entries(self):
        from fabric_tpu.ledger.statedb import Height, VersionedValue

        db = self._cdb()
        # update r1's size; the old entry must leave the index
        db.apply_updates(
            {"cc": {"r1": VersionedValue(
                json.dumps({"color": "red", "size": 6}).encode(),
                Height(2, 0),
            )}},
            Height(2, 1),
        )
        self._check(db, {"color": "red", "size": 5}, [])
        self._check(db, {"color": "red", "size": 6}, ["r1"])
        # delete removes the entry
        db.apply_updates({"cc": {"r2": None}}, Height(3, 1))
        self._check(db, {"color": "red", "size": 9}, [])

    def test_unservable_compound_falls_back_to_single_field(self):
        # a non-scalar operand kills the compound plan at execution
        # time; a coexisting single-field index must still serve the
        # query instead of degrading to the full scan
        db = self._cdb()
        db.define_index("cc", "color")
        from fabric_tpu.ledger.richquery import plan_index

        sel = {"color": "red", "size": [5]}
        p = plan_index(sel, db.indexes_for("cc"))
        assert p[0] == "comp"  # planner prefers the compound index...
        scan, indexed = self._both(db, sel)
        # ...but execution falls back to the color eq index, not None
        assert indexed is not None
        assert [k for k, _, _ in indexed] == scan == ["arr"]

    def test_or_never_rides_the_index(self):
        db = self._cdb()
        scan, indexed = self._both(
            db, {"$or": [{"color": "red"}, {"size": 7}]}
        )
        assert indexed is None  # disjunctions fall back to the scan
        assert scan == ["arr", "b2", "nos", "r1", "r2"]

    def test_randomized_parity_oracle(self):
        import random

        rng = random.Random(20260801)
        colors = ["red", "blue", "", "a" + chr(0) + "b", None, True, 0, 1, 2.5]
        sizes = [0, 1, -1, 2.5, True, False, None, "s", -0.0]
        docs = {}
        for i in range(120):
            d = {}
            if rng.random() < 0.9:
                d["color"] = rng.choice(colors)
            if rng.random() < 0.9:
                d["size"] = rng.choice(sizes)
            if rng.random() < 0.2:
                d["size"] = [1, 2]  # non-indexable
            docs["k%03d" % i] = d
        db = self._db(docs)
        db.define_index("cc", ["color", "size"])
        selectors = []
        for _ in range(60):
            sel = {"color": rng.choice(colors)}
            mode = rng.random()
            if mode < 0.4:
                sel["size"] = rng.choice(sizes)
            elif mode < 0.7:
                lo, hi = sorted(
                    rng.sample([x for x in sizes if isinstance(x, (int, float)) and not isinstance(x, bool)], 2)
                )
                sel["size"] = {"$gte": lo, "$lte": hi}
            else:
                sel["size"] = {"$in": rng.sample(sizes, 3)}
            selectors.append(sel)
        for sel in selectors:
            scan, indexed = self._both(db, sel)
            if indexed is None:
                continue  # planner declined: scan path answered
            assert [k for k, _, _ in indexed] == scan, sel
