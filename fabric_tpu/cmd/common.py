"""Shared CLI plumbing (reference cmd/common + internal/peer/common):
MSP-dir signer loading, endpoint parsing, TLS flags,
proposal/transaction helpers."""

from __future__ import annotations

import argparse
import os

from fabric_tpu import protoutil
from fabric_tpu.comm import RPCClient
from fabric_tpu.csp import SWCSP
from fabric_tpu.msp.identity import SigningIdentity
from fabric_tpu.protos.peer import proposal_pb2, proposal_response_pb2


def parse_endpoint(s: str, default_host: str = "127.0.0.1") -> tuple[str, int]:
    host, _, port = s.rpartition(":")
    return (host or default_host, int(port))


def tls_parent() -> argparse.ArgumentParser:
    """Parent parser contributing the TLS flags every network-touching
    subcommand shares (reference peer CLI --tls/--cafile/--certfile/
    --keyfile; here a cryptogen-layout tls dir + extra roots)."""
    p = argparse.ArgumentParser(add_help=False)
    p.add_argument(
        "--tls-dir", default=None,
        help="dir with {server|client}.{crt,key} + ca.crt (cryptogen tls/)",
    )
    p.add_argument(
        "--tls-root", action="append", default=[],
        help="extra trusted TLS root CA PEM file (repeatable; other orgs)",
    )
    return p


def tls_from_args(args):
    """TLSCredentials from --tls-dir/--tls-root, or None (plaintext)."""
    d = getattr(args, "tls_dir", None)
    if not d:
        return None
    from fabric_tpu.comm.tls import credentials_from_files

    stem = "server" if os.path.exists(os.path.join(d, "server.crt")) else "client"
    return credentials_from_files(
        os.path.join(d, f"{stem}.crt"),
        os.path.join(d, f"{stem}.key"),
        [os.path.join(d, "ca.crt")] + list(getattr(args, "tls_root", []) or []),
    )


def load_signer(msp_dir: str, mspid: str, csp=None) -> SigningIdentity:
    """Load the signing identity from an MSP directory's signcerts +
    keystore (reference msp/configbuilder.go GetLocalMspConfig)."""
    csp = csp or SWCSP()

    def first(sub):
        d = os.path.join(msp_dir, sub)
        names = sorted(os.listdir(d))
        with open(os.path.join(d, names[0]), "rb") as f:
            return f.read()

    return SigningIdentity.from_pem(
        mspid, first("signcerts"), first("keystore"), csp
    )


def endorse(
    peer_endpoints: list[tuple[str, int]],
    signer: SigningIdentity,
    channel_id: str,
    cc_name: str,
    args: list[bytes],
    tls=None,
):
    """Send a signed proposal to each peer; returns (proposal, responses)."""
    prop, _txid = protoutil.create_chaincode_proposal(
        signer.serialize(), channel_id, cc_name, args
    )
    signed = proposal_pb2.SignedProposal(
        proposal_bytes=prop.SerializeToString(),
        signature=signer.sign(prop.SerializeToString()),
    )
    responses = []
    for ep in peer_endpoints:
        raw = RPCClient(*ep, tls=tls).call(
            "endorser.ProcessProposal", signed.SerializeToString()
        )
        responses.append(
            proposal_response_pb2.ProposalResponse.FromString(raw)
        )
    return prop, responses


def submit(
    orderer_endpoint: tuple[str, int],
    signer: SigningIdentity,
    prop,
    responses,
    tls=None,
) -> int:
    """Assemble the signed transaction and broadcast it; returns status."""
    from fabric_tpu.protos.orderer import ab_pb2

    env = protoutil.create_signed_tx(prop, signer, responses)
    raw = RPCClient(*orderer_endpoint, tls=tls).call(
        "ab.Broadcast", env.SerializeToString()
    )
    return ab_pb2.BroadcastResponse.FromString(raw).status


__all__ = ["parse_endpoint", "load_signer", "endorse", "submit",
           "tls_parent", "tls_from_args"]
