// Native BN254 optimal-ate pairing check (the idemix pairing plane).
//
// The reference spends two pure-Go FP256BN.Ate calls per idemix
// signature (idemix/signature.go:290-291); the Python bn254.py oracle
// mirrors that mathematically but runs big-int Fp12 affine lines.  This
// file is the production path: Montgomery Fp (from bn254.cc's layout,
// re-declared here — the TU is compiled into the same .so), Fp2/Fp6/
// Fp12 towers (Fp2 = Fp[i]/(i^2+1), Fp6 = Fp2[v]/(v^3 - (9+i)),
// Fp12 = Fp6[w]/(w^2 - v)), affine twist Miller loop with sparse line
// evaluation (D-type twist: line(P) = yP + (-lam xP) w + (lam x1 - y1)
// w^3), frobenius via precomputed xi-power gammas, and a shared final
// exponentiation (easy part + plain 761-bit hard power).
//
// Exported surface is a single boolean: does prod_i e(P_i, Q_i) == 1 —
// the only form idemix ever consumes (credential ver, weak-BB,
// signature batch/fallback checks).

#include <cstdint>
#include <cstring>

#include "fp254.h"

typedef uint8_t u8;
typedef uint64_t u64;

namespace bnp {

using fp254::Fp;
using fp254::ONE_M;
using fp254::load_fp_be;
using fp254::to_mont;

inline bool fz(const Fp& a) { return fp254::fp_is_zero(a); }
inline void fadd(const Fp& a, const Fp& b, Fp* o) { fp254::fp_add(a, b, o); }
inline void fsub(const Fp& a, const Fp& b, Fp* o) { fp254::fp_sub(a, b, o); }
inline void fneg(const Fp& a, Fp* o) { fp254::fp_neg(a, o); }
inline void fmul(const Fp& a, const Fp& b, Fp* o) { fp254::fp_mul(a, b, o); }
inline void fsqr(const Fp& a, Fp* o) { fp254::fp_sqr(a, o); }
inline void finv(const Fp& a, Fp* o) { fp254::fp_inv(a, o); }

// ---------------------------------------------------------------------------
// Fp2 = Fp[i]/(i^2+1)
// ---------------------------------------------------------------------------

struct F2 {
  Fp a, b;  // a + b i
};

inline bool f2z(const F2& x) { return fz(x.a) && fz(x.b); }

inline void f2add(const F2& x, const F2& y, F2* o) {
  fadd(x.a, y.a, &o->a);
  fadd(x.b, y.b, &o->b);
}

inline void f2sub(const F2& x, const F2& y, F2* o) {
  fsub(x.a, y.a, &o->a);
  fsub(x.b, y.b, &o->b);
}

inline void f2neg(const F2& x, F2* o) {
  fneg(x.a, &o->a);
  fneg(x.b, &o->b);
}

inline void f2conj(const F2& x, F2* o) {
  o->a = x.a;
  fneg(x.b, &o->b);
}

void f2mul(const F2& x, const F2& y, F2* o) {
  Fp t0, t1, t2, sx, sy;
  fmul(x.a, y.a, &t0);
  fmul(x.b, y.b, &t1);
  fadd(x.a, x.b, &sx);
  fadd(y.a, y.b, &sy);
  fmul(sx, sy, &t2);
  F2 r;
  fsub(t0, t1, &r.a);
  fsub(t2, t0, &r.b);
  fsub(r.b, t1, &r.b);
  *o = r;
}

void f2sqr(const F2& x, F2* o) {
  Fp s, d, t;
  fadd(x.a, x.b, &s);
  fsub(x.a, x.b, &d);
  fmul(x.a, x.b, &t);
  F2 r;
  fmul(s, d, &r.a);
  fadd(t, t, &r.b);
  *o = r;
}

void f2inv(const F2& x, F2* o) {
  Fp n, t, d;
  fsqr(x.a, &n);
  fsqr(x.b, &t);
  fadd(n, t, &n);
  finv(n, &d);
  F2 r;
  fmul(x.a, d, &r.a);
  fmul(x.b, d, &t);
  fneg(t, &r.b);
  *o = r;
}

void f2mul_fp(const F2& x, const Fp& k, F2* o) {
  fmul(x.a, k, &o->a);
  fmul(x.b, k, &o->b);
}

// multiply by xi = 9 + i
void f2mul_xi(const F2& x, F2* o) {
  Fp t9a, t9b;
  // 9a: a*8 + a
  Fp a2, a4, a8;
  fadd(x.a, x.a, &a2);
  fadd(a2, a2, &a4);
  fadd(a4, a4, &a8);
  fadd(a8, x.a, &t9a);
  fadd(x.b, x.b, &a2);
  fadd(a2, a2, &a4);
  fadd(a4, a4, &a8);
  fadd(a8, x.b, &t9b);
  F2 r;
  fsub(t9a, x.b, &r.a);  // 9a - b
  fadd(t9b, x.a, &r.b);  // 9b + a
  *o = r;
}

// ---------------------------------------------------------------------------
// Fp6 = Fp2[v]/(v^3 - xi), coeffs (c0, c1, c2)
// ---------------------------------------------------------------------------

struct F6 {
  F2 c0, c1, c2;
};

inline void f6add(const F6& x, const F6& y, F6* o) {
  f2add(x.c0, y.c0, &o->c0);
  f2add(x.c1, y.c1, &o->c1);
  f2add(x.c2, y.c2, &o->c2);
}

inline void f6sub(const F6& x, const F6& y, F6* o) {
  f2sub(x.c0, y.c0, &o->c0);
  f2sub(x.c1, y.c1, &o->c1);
  f2sub(x.c2, y.c2, &o->c2);
}

inline void f6neg(const F6& x, F6* o) {
  f2neg(x.c0, &o->c0);
  f2neg(x.c1, &o->c1);
  f2neg(x.c2, &o->c2);
}

void f6mul(const F6& x, const F6& y, F6* o) {
  F2 v0, v1, v2, t0, t1, t2;
  f2mul(x.c0, y.c0, &v0);
  f2mul(x.c1, y.c1, &v1);
  f2mul(x.c2, y.c2, &v2);
  // c0 = v0 + xi((x1+x2)(y1+y2) - v1 - v2)
  f2add(x.c1, x.c2, &t0);
  f2add(y.c1, y.c2, &t1);
  f2mul(t0, t1, &t2);
  f2sub(t2, v1, &t2);
  f2sub(t2, v2, &t2);
  f2mul_xi(t2, &t2);
  F6 r;
  f2add(t2, v0, &r.c0);
  // c1 = (x0+x1)(y0+y1) - v0 - v1 + xi v2
  f2add(x.c0, x.c1, &t0);
  f2add(y.c0, y.c1, &t1);
  f2mul(t0, t1, &t2);
  f2sub(t2, v0, &t2);
  f2sub(t2, v1, &t2);
  F2 xv2;
  f2mul_xi(v2, &xv2);
  f2add(t2, xv2, &r.c1);
  // c2 = (x0+x2)(y0+y2) - v0 - v2 + v1
  f2add(x.c0, x.c2, &t0);
  f2add(y.c0, y.c2, &t1);
  f2mul(t0, t1, &t2);
  f2sub(t2, v0, &t2);
  f2sub(t2, v2, &t2);
  f2add(t2, v1, &r.c2);
  *o = r;
}

inline void f6sqr(const F6& x, F6* o) { f6mul(x, x, o); }

void f6mul_v(const F6& x, F6* o) {  // * v
  F6 r;
  f2mul_xi(x.c2, &r.c0);
  r.c1 = x.c0;
  r.c2 = x.c1;
  *o = r;
}

void f6inv(const F6& x, F6* o) {
  // c0 = x0^2 - xi x1 x2 ; c1 = xi x2^2 - x0 x1 ; c2 = x1^2 - x0 x2
  F2 A, B, C, t, t2;
  f2sqr(x.c0, &A);
  f2mul(x.c1, x.c2, &t);
  f2mul_xi(t, &t);
  f2sub(A, t, &A);
  f2sqr(x.c2, &t);
  f2mul_xi(t, &B);
  f2mul(x.c0, x.c1, &t);
  f2sub(B, t, &B);
  f2sqr(x.c1, &C);
  f2mul(x.c0, x.c2, &t);
  f2sub(C, t, &C);
  // F = x0 A + xi(x2 B + x1 C)
  F2 F;
  f2mul(x.c2, B, &t);
  f2mul(x.c1, C, &t2);
  f2add(t, t2, &t);
  f2mul_xi(t, &t);
  f2mul(x.c0, A, &t2);
  f2add(t, t2, &F);
  F2 finv2;
  f2inv(F, &finv2);
  f2mul(A, finv2, &o->c0);
  f2mul(B, finv2, &o->c1);
  f2mul(C, finv2, &o->c2);
}

// ---------------------------------------------------------------------------
// Fp12 = Fp6[w]/(w^2 - v), coeffs (d0, d1)
// ---------------------------------------------------------------------------

struct F12 {
  F6 d0, d1;
};

inline void f12mul(const F12& x, const F12& y, F12* o) {
  F6 v0, v1, t0, t1;
  f6mul(x.d0, y.d0, &v0);
  f6mul(x.d1, y.d1, &v1);
  f6add(x.d0, x.d1, &t0);
  f6add(y.d0, y.d1, &t1);
  F12 r;
  f6mul(t0, t1, &t0);
  f6sub(t0, v0, &t0);
  f6sub(t0, v1, &r.d1);
  f6mul_v(v1, &t1);
  f6add(v0, t1, &r.d0);
  *o = r;
}

inline void f12sqr(const F12& x, F12* o) { f12mul(x, x, o); }

inline void f12conj(const F12& x, F12* o) {
  o->d0 = x.d0;
  f6neg(x.d1, &o->d1);
}

void f12inv(const F12& x, F12* o) {
  // (d0 - d1 w)^-1 = (d0 - d1 w)/(d0^2 - v d1^2)
  F6 a, b, t;
  f6sqr(x.d0, &a);
  f6sqr(x.d1, &t);
  f6mul_v(t, &b);
  f6sub(a, b, &a);
  F6 ainv;
  f6inv(a, &ainv);
  f6mul(x.d0, ainv, &o->d0);
  f6mul(x.d1, ainv, &t);
  f6neg(t, &o->d1);
}

void f12_one(F12* o) {
  memset(o, 0, sizeof(F12));
  memcpy(o->d0.c0.a.v, ONE_M, sizeof(ONE_M));
}

bool f12_is_one(const F12& x) {
  F12 one;
  f12_one(&one);
  return memcmp(&x, &one, sizeof(F12)) == 0;
}

// ---------------------------------------------------------------------------
// Sparse line element: L = a + b w + c w^3 with a derived from yP (Fp),
// b = -lam xP (Fp2), c = lam x1 - y1 (Fp2).  In tower coords:
// d0 = (a, 0, 0), d1 = (b, c, 0).
// ---------------------------------------------------------------------------

void f12mul_sparse(const F12& x, const F2& a, const F2& b, const F2& c,
                   F12* o) {
  // y = (a, 0, 0) + ((b, c, 0)) w
  F12 y;
  memset(&y, 0, sizeof(F12));
  y.d0.c0 = a;
  y.d1.c0 = b;
  y.d1.c1 = c;
  f12mul(x, y, o);
}

// ---------------------------------------------------------------------------
// Miller loop over the affine twist.
// ---------------------------------------------------------------------------

struct G2A {
  F2 x, y;
  bool inf;
};

// ate loop bits of 6u+2, MSB first, skipping the leading 1 (65-bit value)
static const char* ATE_BITS =
    "1001110101111001011100000011100110111110011101100011101110101000";

// frobenius gammas (Montgomery Fp2 built at init)
struct Gammas {
  F2 g12, g13;
  Fp g22, g23;
  bool ready = false;
};
static Gammas G;

void init_gammas() {
  if (G.ready) return;
  static const u64 g12a[4] = {0x99e39557176f553dULL, 0xb78cc310c2c3330cULL,
                              0x4c0bec3cf559b143ULL, 0x2fb347984f7911f7ULL};
  static const u64 g12b[4] = {0x1665d51c640fcba2ULL, 0x32ae2a1d0b7c9dceULL,
                              0x4ba4cc8bd75a0794ULL, 0x16c9e55061ebae20ULL};
  static const u64 g13a[4] = {0xdc54014671a0135aULL, 0xdbaae0eda9c95998ULL,
                              0xdc5ec698b6e2f9b9ULL, 0x063cf305489af5dcULL};
  static const u64 g13b[4] = {0x82d37f632623b0e3ULL, 0x21807dc98fa25bd2ULL,
                              0x0704b5a7ec796f2bULL, 0x07c03cbcac41049aULL};
  static const u64 g22v[4] = {0xe4bd44e5607cfd48ULL, 0xc28f069fbb966e3dULL,
                              0x5e6dd9e7e0acccb0ULL, 0x30644e72e131a029ULL};
  static const u64 g23v[4] = {0x3c208c16d87cfd46ULL, 0x97816a916871ca8dULL,
                              0xb85045b68181585dULL, 0x30644e72e131a029ULL};
  Fp t;
  memcpy(t.v, g12a, 32); to_mont(t, &G.g12.a);
  memcpy(t.v, g12b, 32); to_mont(t, &G.g12.b);
  memcpy(t.v, g13a, 32); to_mont(t, &G.g13.a);
  memcpy(t.v, g13b, 32); to_mont(t, &G.g13.b);
  memcpy(t.v, g22v, 32); to_mont(t, &G.g22);
  memcpy(t.v, g23v, 32); to_mont(t, &G.g23);
  G.ready = true;
}

// Run at .so load (dlopen is single-threaded), so concurrent
// bn254_pairing_check callers never race a lazy init.
struct GammaInit {
  GammaInit() { init_gammas(); }
};
static GammaInit _gamma_init;

// line through t (and q when add) evaluated at P; updates t.
// doubling: q == nullptr.
void line_step(G2A* t, const G2A* q, const Fp& xp, const Fp& yp,
               F2* la, F2* lb, F2* lc, bool* degenerate) {
  *degenerate = false;
  F2 lam, num, den;
  if (q == nullptr) {  // tangent
    F2 x2;
    f2sqr(t->x, &x2);
    F2 three_x2;
    f2add(x2, x2, &three_x2);
    f2add(three_x2, x2, &three_x2);
    F2 two_y;
    f2add(t->y, t->y, &two_y);
    f2inv(two_y, &den);
    f2mul(three_x2, den, &lam);
  } else {
    if (memcmp(&t->x, &q->x, sizeof(F2)) == 0) {
      // vertical (y2 = -y1): line = xP - x1 (w^2 coeff) — degenerate
      // for our use: mark and let caller handle (cannot happen for
      // prime-order inputs in the ate loop)
      *degenerate = true;
      return;
    }
    f2sub(q->y, t->y, &num);
    f2sub(q->x, t->x, &den);
    f2inv(den, &den);
    f2mul(num, den, &lam);
  }
  // line coefficients at P: a = yP ; b = -lam xP ; c = lam x_t - y_t
  memset(la, 0, sizeof(F2));
  la->a = yp;
  F2 t1;
  f2mul_fp(lam, xp, &t1);
  f2neg(t1, lb);
  f2mul(lam, t->x, &t1);
  f2sub(t1, t->y, lc);
  // advance t
  F2 x3, y3;
  f2sqr(lam, &x3);
  f2sub(x3, t->x, &x3);
  if (q == nullptr) {
    f2sub(x3, t->x, &x3);
  } else {
    f2sub(x3, q->x, &x3);
  }
  f2sub(t->x, x3, &y3);
  f2mul(lam, y3, &y3);
  f2sub(y3, t->y, &t->y);
  t->x = x3;
  // t->y currently holds -(correct y)?  y3' = lam (x1 - x3) - y1:
  // computed: y3 = lam(x1 - x3); t->y = y3 - y1. correct.
}

void miller(const Fp& xp, const Fp& yp, const G2A& q, F12* f) {
  G2A t = q;
  f12_one(f);
  bool deg;
  F2 la, lb, lc;
  for (const char* bp = ATE_BITS; *bp; ++bp) {
    F12 fsq;
    f12sqr(*f, &fsq);
    line_step(&t, nullptr, xp, yp, &la, &lb, &lc, &deg);
    f12mul_sparse(fsq, la, lb, lc, f);
    if (*bp == '1') {
      line_step(&t, &q, xp, yp, &la, &lb, &lc, &deg);
      if (!deg) f12mul_sparse(*f, la, lb, lc, f);
    }
  }
  // frobenius corrections: Q1 = pi(Q) = (conj(x) g12, conj(y) g13);
  // Q2 = -pi^2(Q) = (x g22, -y g23)
  G2A q1, q2;
  F2 cx, cy;
  f2conj(q.x, &cx);
  f2conj(q.y, &cy);
  f2mul(cx, G.g12, &q1.x);
  f2mul(cy, G.g13, &q1.y);
  q1.inf = false;
  f2mul_fp(q.x, G.g22, &q2.x);
  f2mul_fp(q.y, G.g23, &q2.y);
  f2neg(q2.y, &q2.y);
  q2.inf = false;
  line_step(&t, &q1, xp, yp, &la, &lb, &lc, &deg);
  if (!deg) f12mul_sparse(*f, la, lb, lc, f);
  line_step(&t, &q2, xp, yp, &la, &lb, &lc, &deg);
  if (!deg) f12mul_sparse(*f, la, lb, lc, f);
}

// hard-part exponent (p^4 - p^2 + 1)/r, little-endian limbs
static const u64 HARD[12] = {
    0xe81bb482ccdf42b1ULL, 0x5abf5cc4f49c36d4ULL, 0xf1154e7e1da014fdULL,
    0xdcc7b44c87cdbacfULL, 0xaaa441e3954bcf8aULL, 0x6b887d56d5095f23ULL,
    0x79581e16f3fd90c6ULL, 0x3b1b1355d189227dULL, 0x4e529a5861876f6bULL,
    0x6c0eb522d5b12278ULL, 0x331ec15183177fafULL, 0x01baaa710b0759adULL};

void frobenius_p2(const F12& x, F12* o);

void final_exp(const F12& f_in, F12* o) {
  // easy: f^(p^6-1) = conj(f) * f^-1 ; then ^(p^2+1)
  F12 f, inv, t;
  f12inv(f_in, &inv);
  f12conj(f_in, &t);
  f12mul(t, inv, &f);
  frobenius_p2(f, &t);
  f12mul(t, f, &f);
  // hard: plain square-and-multiply by HARD (761 bits)
  F12 result;
  bool started = false;
  for (int limb = 11; limb >= 0; --limb)
    for (int bit = 63; bit >= 0; --bit) {
      if (started) f12sqr(result, &result);
      if ((HARD[limb] >> bit) & 1) {
        if (!started) {
          result = f;
          started = true;
        } else {
          f12mul(result, f, &result);
        }
      }
    }
  *o = result;
}

// f^(p^2): coefficient-wise gamma multiplication.  Coefficient at w^k
// (k = 0..5, with Fp6 coeff j at w^(2j), d1 coeffs at w^(2j+1)) maps to
// itself times xi^(k (p^2-1)/6); conjugation is trivial for p^2.
void frobenius_p2(const F12& x, F12* o) {
  // xi^((p^2-1)/6) is in Fp (order divides 6).  gamma2_k = that^k.
  // g22 = xi^((p^2-1)/3) = gamma^2, g23 = xi^((p^2-1)/2) = gamma^3.
  // Recover gamma = g22 * g23^-1 * ... simpler: gamma = xi^((p^2-1)/6)
  // satisfies gamma^2 = g22, gamma^3 = g23 -> gamma = g23 * g22^-1.
  Fp gamma, g22inv;
  finv(G.g22, &g22inv);
  fmul(G.g23, g22inv, &gamma);
  Fp g[6];
  memcpy(g[0].v, ONE_M, sizeof(ONE_M));
  for (int k = 1; k < 6; ++k) fmul(g[k - 1], gamma, &g[k]);
  F12 r;
  f2mul_fp(x.d0.c0, g[0], &r.d0.c0);
  f2mul_fp(x.d0.c1, g[2], &r.d0.c1);
  f2mul_fp(x.d0.c2, g[4], &r.d0.c2);
  f2mul_fp(x.d1.c0, g[1], &r.d1.c0);
  f2mul_fp(x.d1.c1, g[3], &r.d1.c1);
  f2mul_fp(x.d1.c2, g[5], &r.d1.c2);
  *o = r;
}

}  // namespace bnp

extern "C" {

// prod_i e(P_i, Q_i) == 1?  P_i affine G1 (32B BE x, y); Q_i affine
// twist G2 (32B BE x.a, x.b, y.a, y.b).  (0,0) points are skipped
// (identity contributes 1 to the product).  Returns 1 when the product
// is one, 0 otherwise.
int bn254_pairing_check(int n, const u8* pxs, const u8* pys, const u8* qxa,
                        const u8* qxb, const u8* qya, const u8* qyb) {
  using namespace bnp;
  init_gammas();
  F12 acc;
  f12_one(&acc);
  bool any = false;
  for (int i = 0; i < n; ++i) {
    Fp xp_raw, yp_raw, xp, yp;
    load_fp_be(pxs + 32 * i, &xp_raw);
    load_fp_be(pys + 32 * i, &yp_raw);
    if (fz(xp_raw) && fz(yp_raw)) continue;  // P at infinity
    to_mont(xp_raw, &xp);
    to_mont(yp_raw, &yp);
    G2A q;
    Fp t;
    load_fp_be(qxa + 32 * i, &t);
    to_mont(t, &q.x.a);
    load_fp_be(qxb + 32 * i, &t);
    to_mont(t, &q.x.b);
    load_fp_be(qya + 32 * i, &t);
    to_mont(t, &q.y.a);
    load_fp_be(qyb + 32 * i, &t);
    to_mont(t, &q.y.b);
    if (f2z(q.x) && f2z(q.y)) continue;  // Q at infinity
    q.inf = false;
    F12 f;
    miller(xp, yp, q, &f);
    f12mul(acc, f, &acc);
    any = true;
  }
  if (!any) return 1;
  F12 out;
  final_exp(acc, &out);
  return f12_is_one(out) ? 1 : 0;
}

}  // extern "C"
