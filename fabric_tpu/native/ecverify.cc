// Host-side batched ECDSA-P256 verification over libcrypto (dlopen'd,
// like collect.cc's SHA dispatch — no link-time OpenSSL dependency).
//
// Purpose: the TPU provider's stall fallback (csp/tpu/provider.py
// _FlushResult._host_race) must verify a whole flush on the host as
// fast as the machine allows — OpenSSL's vectorized nistz256 verify is
// ~2-4x the python-wrapped path (each python call pays DER re-marshal
// plus wrapper overhead), which is the difference between a chip stall
// costing ~150 ms and ~450 ms at p99.  The BASELINE bench path keeps
// the python-per-signature engine: it models the reference's serial
// cost structure (bccsp/sw/ecdsa.go:41) and is not wired to this.
//
// Semantics mirror csp/sw.py _verify_one exactly: DER-strict parse,
// r,s in [1, n-1], LOW-S enforced, then curve verification.

#include <cstdint>
#include <cstring>
#include <map>
#include <string>
#include <vector>

#include <dlfcn.h>

typedef uint8_t u8;
typedef int32_t i32;

namespace {

// -- libcrypto symbols.  Keys are built through the legacy EC_KEY API
// (simplest route from affine coordinates) but verification goes
// through EVP_PKEY_verify: on OpenSSL 3.x a bare ECDSA_do_verify pays
// the legacy->provider bridge PER CALL (~40x slower), while an
// EVP_PKEY wrapping the key exports to the provider once and every
// subsequent verify runs the optimized implementation.
struct Ossl {
  void* (*BN_bin2bn)(const u8*, int, void*) = nullptr;
  void (*BN_free)(void*) = nullptr;
  void* (*EC_KEY_new_by_curve_name)(int) = nullptr;
  void (*EC_KEY_free)(void*) = nullptr;
  int (*EC_KEY_set_public_key_affine_coordinates)(void*, void*, void*) =
      nullptr;
  void* (*EVP_PKEY_new)() = nullptr;
  void (*EVP_PKEY_free)(void*) = nullptr;
  int (*EVP_PKEY_set1_EC_KEY)(void*, void*) = nullptr;
  void* (*EVP_PKEY_CTX_new)(void*, void*) = nullptr;
  void (*EVP_PKEY_CTX_free)(void*) = nullptr;
  int (*EVP_PKEY_verify_init)(void*) = nullptr;
  int (*EVP_PKEY_verify)(void*, const u8*, size_t, const u8*, size_t) =
      nullptr;
  bool ok = false;
};

const Ossl& ossl() {
  static const Ossl o = [] {
    Ossl s;
    for (const char* name :
         {"libcrypto.so.3", "libcrypto.so.1.1", "libcrypto.so"}) {
      void* h = dlopen(name, RTLD_NOW | RTLD_LOCAL);
      if (!h) continue;
      s.BN_bin2bn =
          reinterpret_cast<void* (*)(const u8*, int, void*)>(
              dlsym(h, "BN_bin2bn"));
      s.BN_free = reinterpret_cast<void (*)(void*)>(dlsym(h, "BN_free"));
      s.EC_KEY_new_by_curve_name = reinterpret_cast<void* (*)(int)>(
          dlsym(h, "EC_KEY_new_by_curve_name"));
      s.EC_KEY_free =
          reinterpret_cast<void (*)(void*)>(dlsym(h, "EC_KEY_free"));
      s.EC_KEY_set_public_key_affine_coordinates =
          reinterpret_cast<int (*)(void*, void*, void*)>(
              dlsym(h, "EC_KEY_set_public_key_affine_coordinates"));
      s.EVP_PKEY_new =
          reinterpret_cast<void* (*)()>(dlsym(h, "EVP_PKEY_new"));
      s.EVP_PKEY_free =
          reinterpret_cast<void (*)(void*)>(dlsym(h, "EVP_PKEY_free"));
      s.EVP_PKEY_set1_EC_KEY = reinterpret_cast<int (*)(void*, void*)>(
          dlsym(h, "EVP_PKEY_set1_EC_KEY"));
      s.EVP_PKEY_CTX_new = reinterpret_cast<void* (*)(void*, void*)>(
          dlsym(h, "EVP_PKEY_CTX_new"));
      s.EVP_PKEY_CTX_free =
          reinterpret_cast<void (*)(void*)>(dlsym(h, "EVP_PKEY_CTX_free"));
      s.EVP_PKEY_verify_init = reinterpret_cast<int (*)(void*)>(
          dlsym(h, "EVP_PKEY_verify_init"));
      s.EVP_PKEY_verify =
          reinterpret_cast<int (*)(void*, const u8*, size_t, const u8*,
                                   size_t)>(dlsym(h, "EVP_PKEY_verify"));
      if (s.BN_bin2bn && s.BN_free && s.EC_KEY_new_by_curve_name &&
          s.EC_KEY_free && s.EC_KEY_set_public_key_affine_coordinates &&
          s.EVP_PKEY_new && s.EVP_PKEY_free && s.EVP_PKEY_set1_EC_KEY &&
          s.EVP_PKEY_CTX_new && s.EVP_PKEY_CTX_free &&
          s.EVP_PKEY_verify_init && s.EVP_PKEY_verify) {
        s.ok = true;
        break;
      }
      dlclose(h);
    }
    return s;
  }();
  return o;
}

const int NID_P256 = 415;  // NID_X9_62_prime256v1

// P-256 group order n and n/2 (low-S bound), big-endian.
const u8 P256_N[32] = {
    0xff, 0xff, 0xff, 0xff, 0x00, 0x00, 0x00, 0x00,
    0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
    0xbc, 0xe6, 0xfa, 0xad, 0xa7, 0x17, 0x9e, 0x84,
    0xf3, 0xb9, 0xca, 0xc2, 0xfc, 0x63, 0x25, 0x51};
const u8 P256_HALF_N[32] = {
    0x7f, 0xff, 0xff, 0xff, 0x80, 0x00, 0x00, 0x00,
    0x7f, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
    0xde, 0x73, 0x7d, 0x56, 0xd3, 0x8b, 0xcf, 0x42,
    0x79, 0xdc, 0xe5, 0x61, 0x7e, 0x31, 0x92, 0xa8};

// big-endian compare of 32-byte values: returns <0, 0, >0
int cmp32(const u8* a, const u8* b) { return memcmp(a, b, 32); }

bool is_zero32(const u8* a) {
  for (int i = 0; i < 32; ++i)
    if (a[i]) return false;
  return true;
}

// Strict-DER ECDSA signature parse into 32-byte big-endian r, s
// (mirrors csp/api.py unmarshal_ecdsa_signature: exact lengths, no
// negative integers, minimal encoding).
bool parse_der(const u8* sig, int n, u8* r32, u8* s32) {
  auto read_int = [&](int& pos, u8* out) -> bool {
    if (pos + 2 > n || sig[pos] != 0x02) return false;
    int len = sig[pos + 1];
    pos += 2;
    if (len <= 0 || len > 33 || pos + len > n) return false;
    const u8* p = sig + pos;
    if (p[0] & 0x80) return false;                       // negative
    if (len > 1 && p[0] == 0x00 && !(p[1] & 0x80)) return false;  // non-minimal
    int skip = (len == 33) ? 1 : 0;
    if (skip && p[0] != 0x00) return false;              // 33 bytes must pad
    int eff = len - skip;
    if (eff > 32) return false;
    memset(out, 0, 32);
    memcpy(out + (32 - eff), p + skip, eff);
    pos += len;
    return true;
  };
  if (n < 8 || sig[0] != 0x30) return false;
  int body = sig[1];
  if (body != n - 2) return false;  // no long-form, exact length
  int pos = 2;
  if (!read_int(pos, r32)) return false;
  if (!read_int(pos, s32)) return false;
  return pos == n;
}

}  // namespace

extern "C" {

// Verify n (key, digest, DER signature) triples on the host.
// qxy: n*64 bytes (32-byte big-endian x || y per lane);
// digests: n*32; sigs + sig_off/sig_len: concatenated DER signatures.
// out[i] = 1 valid / 0 invalid.  Returns 0 on success, -1 when
// libcrypto is unavailable (caller falls back to the python engine).
int fabric_ecdsa_verify_host(int n, const u8* qxy, const u8* digests,
                             const u8* sigs, const i32* sig_off,
                             const i32* sig_len, u8* out) {
  const Ossl& o = ossl();
  if (!o.ok) return -1;
  // Per-key cache of a ready EVP_PKEY_CTX: a block's lanes repeat a
  // handful of endorser/creator keys; the affine-coordinate on-curve
  // check, the EVP wrap (one provider export), and the verify-init are
  // all paid once per distinct key, not once per lane.
  struct KeyCtx {
    void* pkey = nullptr;
    void* ctx = nullptr;
  };
  std::map<std::string, KeyCtx> keys;  // 64-byte q -> ctx (null = bad)
  for (int i = 0; i < n; ++i) {
    out[i] = 0;
    u8 r32[32], s32[32];
    if (!parse_der(sigs + sig_off[i], sig_len[i], r32, s32)) continue;
    // r, s in [1, n-1]; LOW-S enforced (sw.py rejects high-S before
    // curve math, as the reference does)
    if (is_zero32(r32) || is_zero32(s32)) continue;
    if (cmp32(r32, P256_N) >= 0 || cmp32(s32, P256_N) >= 0) continue;
    if (cmp32(s32, P256_HALF_N) > 0) continue;

    std::string kb(reinterpret_cast<const char*>(qxy + 64 * size_t(i)), 64);
    auto it = keys.find(kb);
    if (it == keys.end()) {
      KeyCtx kc;
      void* eckey = o.EC_KEY_new_by_curve_name(NID_P256);
      if (eckey) {
        void* bx = o.BN_bin2bn(qxy + 64 * size_t(i), 32, nullptr);
        void* by = o.BN_bin2bn(qxy + 64 * size_t(i) + 32, 32, nullptr);
        int okk = (bx && by)
                      ? o.EC_KEY_set_public_key_affine_coordinates(eckey, bx,
                                                                   by)
                      : 0;
        if (bx) o.BN_free(bx);
        if (by) o.BN_free(by);
        if (okk) {
          kc.pkey = o.EVP_PKEY_new();
          if (kc.pkey && o.EVP_PKEY_set1_EC_KEY(kc.pkey, eckey) == 1) {
            kc.ctx = o.EVP_PKEY_CTX_new(kc.pkey, nullptr);
            if (kc.ctx && o.EVP_PKEY_verify_init(kc.ctx) != 1) {
              o.EVP_PKEY_CTX_free(kc.ctx);
              kc.ctx = nullptr;
            }
          }
          if (!kc.ctx && kc.pkey) {
            o.EVP_PKEY_free(kc.pkey);
            kc.pkey = nullptr;
          }
        }
        o.EC_KEY_free(eckey);  // pkey holds its own reference
      }
      it = keys.emplace(std::move(kb), kc).first;
    }
    if (!it->second.ctx) continue;
    out[i] = o.EVP_PKEY_verify(it->second.ctx, sigs + sig_off[i],
                               size_t(sig_len[i]),
                               digests + 32 * size_t(i), 32) == 1
                 ? 1
                 : 0;
  }
  for (auto& kv : keys) {
    if (kv.second.ctx) o.EVP_PKEY_CTX_free(kv.second.ctx);
    if (kv.second.pkey) o.EVP_PKEY_free(kv.second.pkey);
  }
  return 0;
}

}  // extern "C"
