"""SEEDED VIOLATION (racecheck): the spawned thread target is a
LOCALLY-DEFINED closure — invisible to the lockset pass until PR 8
resolved nested defs into the thread-entry set (the committer's
commit_loop pattern).  Its unguarded write must fire."""

from fabric_tpu.devtools.lockwatch import named_lock, spawn_thread


class StreamPump:
    def __init__(self):
        self._lock = named_lock("fixture.pump")
        self._done = {}

    def start(self):
        def pump_loop():
            self._done["n"] = 1  # <- racecheck fires HERE

        t = spawn_thread(
            target=pump_loop, name="fixture-pump", kind="worker"
        )
        t.start()
        return t

    def mark(self):
        with self._lock:
            self._done["m"] = 2

    def poll(self):
        with self._lock:
            return self._done.get("n")
