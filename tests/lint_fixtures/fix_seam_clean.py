"""CLEAN TWIN of fix_seam_dirty: the same two-function shape routed
through the CSP hash seam."""

from fabric_tpu.common.hashing import sha256


def _fingerprint(data: bytes) -> bytes:
    return sha256(data)


def catalog_key(data: bytes) -> bytes:
    return _fingerprint(data)
