"""Seeded randomized fuzzing of the envelope ingestion surface.

The reference runs its validation stack under Go's race detector and
fuzzes protobuf ingestion; the equivalents here (SURVEY.md §5 "race
detection / sanitizers") are deterministic, seeded mutation sweeps
over the three consensus-relevant properties:

1. **Engine parity** — the native C++ collect pass (collect.cc) and the
   pure-Python collect must produce IDENTICAL validation flags for any
   input, however mangled (flags are consensus state: a divergence is a
   fork, exactly why the reference keeps one canonical implementation).
2. **Determinism** — validating the same mangled block twice yields the
   same flags.
3. **No crashes, commit safety** — mangled blocks flow through
   validate + ledger commit without exceptions, and the valid lanes'
   writes still land.

Plus a direct memory-safety sweep of the native wire walker on
arbitrary buffers (the C++ code parses attacker-controlled bytes; a
segfault there takes down the peer).

The corpus is structured: byte flips, truncations, insertions, slice
duplications, and wire-level field replacements at random nesting
depths.  Mutation CHOICES are seeded, but the base envelope embeds
fresh nonces/signatures per process, so every run explores new bytes —
assertions dump the offending mutant hex for reproduction.  This
harness has earned its keep: it found an out-of-bounds write in
collect.cc's field-number decoding (a huge tag varint truncated to a
negative array index) and a flag-parity divergence between the two
collect engines on half-parseable envelopes.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from orgfix import make_org

from fabric_tpu import native, protoutil
from fabric_tpu.common import configtx_builder as ctx
from fabric_tpu.common.channelconfig import bundle_from_genesis
from fabric_tpu.ledger import LedgerProvider
from fabric_tpu.peer.committer import Committer
from fabric_tpu.peer.endorser import Endorser
from fabric_tpu.peer.txvalidator import TxValidator
from fabric_tpu.protos.common import common_pb2
from fabric_tpu.protos.peer import proposal_pb2, transaction_pb2

V = transaction_pb2


def _cc(sim, args):
    sim.set_state("fuzzcc", args[0].decode(), args[1])
    return 200, "", b""


@pytest.fixture(scope="module")
def world():
    from fabric_tpu.msp import msp_config_from_ca

    org = make_org("Org1MSP")
    oorg = make_org("OrdererMSP")
    app = ctx.application_group(
        {"Org1": ctx.org_group(
            "Org1MSP", msp_config_from_ca(org.ca, "Org1MSP")
        )}
    )
    ordg = ctx.orderer_group(
        {"O": ctx.org_group(
            "OrdererMSP", msp_config_from_ca(oorg.ca, "OrdererMSP")
        )},
        consensus_type="solo",
    )
    genesis = ctx.genesis_block("fuzzch", ctx.channel_group(app, ordg))
    bundle_csp = org.csp
    endorser_signer = org.signer("peer0", role_ou="peer")
    client = org.signer("user1", role_ou="client")

    def fresh_ledger():
        return LedgerProvider(None).create(genesis)

    ledger = fresh_ledger()
    bundle = bundle_from_genesis(genesis, bundle_csp)
    endorser = Endorser(
        "fuzzch", ledger, bundle, endorser_signer, {"fuzzcc": _cc}, org.csp,
    )
    return org, genesis, bundle, endorser, client, fresh_ledger


_counter = [0]


def _tx_bytes(endorser, client) -> bytes:
    _counter[0] += 1
    prop, _ = protoutil.create_chaincode_proposal(
        client.serialize(), "fuzzch", "fuzzcc",
        [b"k%d" % _counter[0], b"v"],
    )
    signed = proposal_pb2.SignedProposal(
        proposal_bytes=prop.SerializeToString(),
        signature=client.sign(prop.SerializeToString()),
    )
    resp = endorser.process_proposal(signed)
    return protoutil.create_signed_tx(
        prop, client, [resp]
    ).SerializeToString()


def _byte_mutants(rng: random.Random, base: bytes, n: int) -> list[bytes]:
    out = []
    for _ in range(n):
        kind = rng.randrange(4)
        b = bytearray(base)
        if kind == 0 and b:  # flip a byte
            i = rng.randrange(len(b))
            b[i] ^= 1 << rng.randrange(8)
        elif kind == 1 and b:  # truncate
            b = b[: rng.randrange(len(b))]
        elif kind == 2:  # insert random bytes
            i = rng.randrange(len(b) + 1)
            ins = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 9)))
            b = b[:i] + ins + b[i:]
        else:  # duplicate a slice
            if len(b) >= 2:
                i = rng.randrange(len(b) - 1)
                j = rng.randrange(i + 1, min(len(b), i + 64))
                b = b[:j] + b[i:j] + b[j:]
        out.append(bytes(b))
    return out


def _wire_mutants(rng: random.Random, base: bytes, n: int) -> list[bytes]:
    """Decode-mutate-reencode at a random nesting level: payload,
    header fields, or the transaction body get replaced with garbage,
    emptied, or swapped."""
    out = []
    for _ in range(n):
        try:
            env = common_pb2.Envelope.FromString(base)
            p = common_pb2.Payload.FromString(env.payload)
        except Exception:
            continue
        target = rng.randrange(6)
        junk = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 24)))
        if target == 0:
            env.payload = junk
        elif target == 1:
            env.signature = junk
        elif target == 2:
            p.header.channel_header = junk
            env.payload = p.SerializeToString()
        elif target == 3:
            p.header.signature_header = junk
            env.payload = p.SerializeToString()
        elif target == 4:
            p.data = junk
            env.payload = p.SerializeToString()
        else:
            try:
                tx = transaction_pb2.Transaction.FromString(p.data)
                if tx.actions:
                    tx.actions[0].payload = junk
                p.data = tx.SerializeToString()
                env.payload = p.SerializeToString()
            except Exception:
                env.payload = junk
        out.append(env.SerializeToString())
    return out


def _block(env_bytes: list[bytes], num: int = 1) -> common_pb2.Block:
    blk = common_pb2.Block()
    blk.header.number = num
    for raw in env_bytes:
        blk.data.data.append(raw)
    blk.header.data_hash = protoutil.block_data_hash(blk.data)
    protoutil.init_block_metadata(blk)
    protoutil.set_tx_filter(blk, bytearray(len(env_bytes)))
    return blk


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_fuzz_flag_parity_and_determinism(world, seed):
    """Property 1 + 2: for every mangled block, native and pure-Python
    collect agree flag-for-flag, twice."""
    org, genesis, bundle, endorser, client, fresh_ledger = world
    rng = random.Random(1000 + seed)
    base = _tx_bytes(endorser, client)
    mutants = (
        _byte_mutants(rng, base, 24) + _wire_mutants(rng, base, 16)
    )
    # batch them with one untouched tx so the happy path stays covered
    batch = [_tx_bytes(endorser, client)] + mutants
    rng.shuffle(batch)

    flags_a = TxValidator(
        "fuzzch", fresh_ledger(), bundle, org.csp
    ).validate(_block(list(batch)))
    v_py = TxValidator("fuzzch", fresh_ledger(), bundle, org.csp)
    v_py._collect_native = lambda *a, **k: False
    flags_b = v_py.validate(_block(list(batch)))
    if flags_a != flags_b:  # dump the diverging lanes for reproduction
        bad = [
            (i, fa, fb, batch[i].hex())
            for i, (fa, fb) in enumerate(zip(flags_a, flags_b))
            if fa != fb
        ]
        raise AssertionError(f"engine flag divergence: {bad}")
    if native.available():
        flags_c = TxValidator(
            "fuzzch", fresh_ledger(), bundle, org.csp
        ).validate(_block(list(batch)))
        assert flags_a == flags_c  # deterministic
    assert flags_a.count(V.VALID) >= 1  # the untouched tx survived


@pytest.mark.parametrize("seed", [7, 8])
def test_fuzz_blocks_commit_safely(world, seed):
    """Property 3: mangled blocks flow through validate + commit; the
    valid lane's write lands, invalid lanes contribute nothing."""
    org, genesis, bundle, endorser, client, fresh_ledger = world
    rng = random.Random(2000 + seed)
    ledger = fresh_ledger()
    committer = Committer(
        TxValidator("fuzzch", ledger, bundle, org.csp), ledger
    )
    base = _tx_bytes(endorser, client)
    for num in (1, 2):
        good = _tx_bytes(endorser, client)
        batch = _byte_mutants(rng, base, 8) + [good] + _wire_mutants(
            rng, base, 6
        )
        flags = committer.store_block(_block(list(batch), num=num))
        assert flags[batch.index(good)] == V.VALID
        assert ledger.height == num + 1
    # the good txs' writes are queryable state
    assert ledger.get_state("fuzzcc", "k%d" % _counter[0]) == b"v"


@pytest.mark.skipif(not native.available(), reason="native unavailable")
def test_invalid_utf8_string_field_parity(world):
    """Deterministic regression for the class the fuzzer surfaced: a
    proto3 string field with invalid UTF-8 in a spot that does NOT
    break the proposal-hash binding (Response.message inside
    ChaincodeAction).  Python's protobuf rejects the ChaincodeAction
    parse (BAD_PAYLOAD); the C++ walker, which treats strings as bytes,
    must detect the invalid UTF-8 and hand the lane to the python
    collector instead of calling the tx well-formed — and the glue's
    .decode() must never blow up the whole block."""
    org, genesis, bundle, endorser, client, fresh_ledger = world
    env = common_pb2.Envelope.FromString(_tx_bytes(endorser, client))
    p = common_pb2.Payload.FromString(env.payload)
    tx = transaction_pb2.Transaction.FromString(p.data)
    cap = transaction_pb2.ChaincodeActionPayload.FromString(
        tx.actions[0].payload
    )
    from fabric_tpu.protos.peer import proposal_response_pb2

    prp = proposal_response_pb2.ProposalResponsePayload.FromString(
        cap.action.proposal_response_payload
    )
    # append a Response{message=b'\xff'} submessage at the wire level
    # (python's API cannot hold invalid UTF-8 in a str field): field 3
    # wt 2, body = field 2 wt 2 len 1 0xff — last/merged occurrence wins
    prp.extension = prp.extension + bytes([0x1A, 0x03, 0x12, 0x01, 0xFF])
    cap.action.proposal_response_payload = prp.SerializeToString()
    tx.actions[0].payload = cap.SerializeToString()
    p.data = tx.SerializeToString()
    pb = p.SerializeToString()
    mangled = common_pb2.Envelope(
        payload=pb, signature=client.sign(pb)
    ).SerializeToString()

    good = _tx_bytes(endorser, client)
    batch = [good, mangled]
    flags_native = TxValidator(
        "fuzzch", fresh_ledger(), bundle, org.csp
    ).validate(_block(list(batch)))
    v_py = TxValidator("fuzzch", fresh_ledger(), bundle, org.csp)
    v_py._collect_native = lambda *a, **k: False
    flags_py = v_py.validate(_block(list(batch)))
    assert flags_native == flags_py
    assert flags_native[0] == V.VALID
    assert flags_native[1] == V.BAD_PAYLOAD


def test_malformed_ccpp_flags_instead_of_raising(world):
    """Regression for the wire-fuzzer's second find, updated for
    GetProposalHash2 semantics: the committed ChaincodeProposalPayload
    is hashed raw and never parsed (reference msgvalidation.go:233), so
    garbage ccpp bytes can neither raise out of validate() nor fork the
    engines — they simply break the hash binding.  Both engines must
    flag the lane BAD_RESPONSE_PAYLOAD and keep going."""
    org, genesis, bundle, endorser, client, fresh_ledger = world
    env = common_pb2.Envelope.FromString(_tx_bytes(endorser, client))
    p = common_pb2.Payload.FromString(env.payload)
    tx = transaction_pb2.Transaction.FromString(p.data)
    cap = transaction_pb2.ChaincodeActionPayload.FromString(
        tx.actions[0].payload
    )
    cap.chaincode_proposal_payload = b"\xff\xff\xff"
    tx.actions[0].payload = cap.SerializeToString()
    p.data = tx.SerializeToString()
    pb = p.SerializeToString()
    mangled = common_pb2.Envelope(
        payload=pb, signature=client.sign(pb)
    ).SerializeToString()
    batch = [_tx_bytes(endorser, client), mangled]
    for force_py in (False, True):
        v = TxValidator("fuzzch", fresh_ledger(), bundle, org.csp)
        if force_py:
            v._collect_native = lambda *a, **k: False
        flags = v.validate(_block(list(batch)))
        assert flags == [V.VALID, V.BAD_RESPONSE_PAYLOAD], (force_py, flags)


def test_transient_map_in_committed_ccpp_parity(world):
    """Advisor regression (round 4, high): a committed ccpp that still
    carries a PARSEABLE TransientMap.  Under the old GetProposalHash1
    validation the python engine re-parsed and stripped the transient
    (hash matched -> VALID) while the reference rejects the tx (raw
    bytes differ from the endorsed preimage) — and the native walker's
    canonical-walk handling of field 2 forked the engines.  Under
    GetProposalHash2 both engines hash the committed bytes raw: the
    smuggled transient breaks the binding and BOTH flag
    BAD_RESPONSE_PAYLOAD, matching the reference."""
    org, genesis, bundle, endorser, client, fresh_ledger = world
    from fabric_tpu.protos.peer import proposal_pb2

    env = common_pb2.Envelope.FromString(_tx_bytes(endorser, client))
    p = common_pb2.Payload.FromString(env.payload)
    tx = transaction_pb2.Transaction.FromString(p.data)
    cap = transaction_pb2.ChaincodeActionPayload.FromString(
        tx.actions[0].payload
    )
    ccpp = proposal_pb2.ChaincodeProposalPayload.FromString(
        cap.chaincode_proposal_payload
    )
    ccpp.TransientMap["secret"] = b"smuggled"
    # sanity: the OLD filtered hash is unchanged by the transient entry,
    # i.e. this envelope would have validated under Hash1 semantics
    from fabric_tpu import protoutil as pu

    prp_old = pu.proposal_hash(
        p.header.channel_header,
        p.header.signature_header,
        ccpp.SerializeToString(),
    )
    assert prp_old == pu.proposal_hash(
        p.header.channel_header,
        p.header.signature_header,
        cap.chaincode_proposal_payload,
    )
    cap.chaincode_proposal_payload = ccpp.SerializeToString()
    tx.actions[0].payload = cap.SerializeToString()
    p.data = tx.SerializeToString()
    pb = p.SerializeToString()
    mangled = common_pb2.Envelope(
        payload=pb, signature=client.sign(pb)
    ).SerializeToString()
    batch = [_tx_bytes(endorser, client), mangled]
    for force_py in (False, True):
        v = TxValidator("fuzzch", fresh_ledger(), bundle, org.csp)
        if force_py:
            v._collect_native = lambda *a, **k: False
        flags = v.validate(_block(list(batch)))
        assert flags == [V.VALID, V.BAD_RESPONSE_PAYLOAD], (force_py, flags)


@pytest.mark.skipif(not native.available(), reason="native unavailable")
def test_fuzz_native_walker_memory_safety(world):
    """The C++ wire walker must survive arbitrary buffers, STRUCTURED
    mutants of real envelopes (these reach the deep wire paths — a
    byte-flipped tag once truncated to a negative field index and wrote
    out of bounds), and odd offset splits — without crashing the
    process, reporting only known status codes."""
    org, genesis, bundle, endorser, client, fresh_ledger = world
    rng = random.Random(31337)
    known = set(range(-13, 2))
    base = _tx_bytes(endorser, client)

    def check(chunks, trial):
        offs = [0]
        for c in chunks:
            offs.append(offs[-1] + len(c))
        co = native.collect_block(
            b"".join(chunks), np.asarray(offs, np.int64), b"fuzzch"
        )
        if co is not None:
            for st in co["status"].tolist():
                assert st in known, (trial, st)

    for trial in range(100):  # pure garbage buffers
        check(
            [
                bytes(
                    rng.randrange(256)
                    for _ in range(rng.randrange(0, 300))
                )
                for _ in range(rng.randrange(1, 5))
            ],
            trial,
        )
    for trial in range(300):  # structured mutants of a real envelope
        check(_byte_mutants(rng, base, rng.randrange(1, 4)), trial)
