"""Seeded violation (racecheck, v5 CFG pass): the empty-buffer early
path releases the lock and THEN writes the shared field — lexically
inside the acquire/release span, but past the release on its own path.
Only the per-program-point lockset sees the hole."""

import threading

from fabric_tpu.devtools.lockwatch import spawn_thread


class Spool:
    def __init__(self):
        self._lock = threading.Lock()
        self._buf = []
        self._stop = threading.Event()

    def serve(self):
        t = spawn_thread(
            target=self._run, name="spool", kind="service"
        )
        t.start()
        return t

    def stop(self):
        self._stop.set()

    def _run(self):
        while not self._stop.is_set():
            self.drain()

    def drain(self):
        self._lock.acquire()
        if not self._buf:
            self._lock.release()
            self._buf = []  # <- released on this path: fires HERE
            return []
        items = list(self._buf)
        self._buf = []
        self._lock.release()
        return items

    def push(self, item):
        with self._lock:
            self._buf.append(item)

    def peek(self):
        with self._lock:
            return list(self._buf)
