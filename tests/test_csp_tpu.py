"""TPU provider parity vs the sw oracle (hash + verify batch APIs)."""

import hashlib
import random

from fabric_tpu.csp import SWCSP, VerifyBatchItem, api, init_factories
from fabric_tpu.csp.tpu.provider import TPUCSP


def test_factory_selects_tpu():
    csp = init_factories("tpu", force=True)
    assert isinstance(csp, TPUCSP)
    init_factories("sw", force=True)


def test_hash_batch_parity():
    rng = random.Random(3)
    csp = TPUCSP(min_device_batch=1)
    msgs = [bytes(rng.randrange(256) for _ in range(rng.randrange(0, 200))) for _ in range(37)]
    msgs += [b"", b"a" * 55, b"a" * 56, b"a" * 64, b"a" * 119, b"a" * 120]
    got = csp.hash_batch(msgs)
    want = [hashlib.sha256(m).digest() for m in msgs]
    assert got == want


def test_verify_batch_parity_with_tampering():
    rng = random.Random(11)
    sw = SWCSP()
    tpu = TPUCSP(sw=sw, min_device_batch=1)
    items = []
    for i in range(40):
        key = sw.key_gen()
        digest = sw.hash(b"payload-%d" % i)
        sig = sw.sign(key, digest)
        roll = rng.random()
        if roll < 0.15:
            sig = sig[:-2] + bytes([sig[-2] ^ 1, sig[-1]])
        elif roll < 0.25:
            digest = sw.hash(b"evil-%d" % i)
        elif roll < 0.3:
            sig = b"\x30\x02\x01\x01"  # malformed DER
        elif roll < 0.35:
            r, s = api.unmarshal_ecdsa_signature(sig)
            sig = api.marshal_ecdsa_signature(r, api.P256_N - s)  # high-S
        items.append(VerifyBatchItem(key.public_key(), digest, sig))
    got = tpu.verify_batch(items)
    want = sw.verify_batch(items)
    assert got == want
    assert any(got) and not all(got)


def test_verify_batch_small_falls_back_to_host():
    sw = SWCSP()
    tpu = TPUCSP(sw=sw, min_device_batch=64)
    key = sw.key_gen()
    d = sw.hash(b"x")
    items = [VerifyBatchItem(key.public_key(), d, sw.sign(key, d))]
    assert tpu.verify_batch(items) == [True]


# -- flush waiter / deadline host-race mechanics -------------------------


def _signed_items(n, sw=None):
    sw = sw or SWCSP()
    key = sw.key_gen()
    out = []
    for i in range(n):
        d = sw.hash(b"race-%d" % i)
        sig = sw.sign(key, d)
        if i % 5 == 4:
            sig = b"\x30\x02\x01\x01"  # invalid lane
        out.append(VerifyBatchItem(key.public_key(), d, sig))
    return out


def test_deadline_ewma_budget(monkeypatch):
    """The stall deadline is a latency budget: host anchor until the
    EWMA is primed, then 1.5x the predicted flush wall clamped to
    [0.15s, anchor] — so ordinary windows race early while a starved
    chip window cannot inflate its own deadline past the host cost."""
    import fabric_tpu.csp.tpu.provider as prov

    # the process-wide measured host rate (fed by other tests' host
    # races) must not leak into these exact-equality assertions
    monkeypatch.setattr(prov, "_host_rate_ewma", [None])
    csp = TPUCSP(stall_factor=1.0, host_rate_hint=10000.0)
    # unprimed: the anchor (lanes/host_rate, floor 0.2)
    assert csp._deadline_for(4000) == 0.4
    assert csp._deadline_for(100) == 0.2
    # primed with a fast chip: tight budget, floored at 0.15
    for _ in range(4):
        csp._note_device_wall(4000, 0.08)  # 20 us/lane -> 50 klane/s
    d = csp._deadline_for(4000)
    assert abs(d - 0.15) < 1e-9 or d < 0.2  # 1.5*0.08=0.12 -> floor 0.15
    # a big flush scales linearly but stays under the anchor
    d = csp._deadline_for(16000)
    assert 0.15 <= d <= 1.6
    assert abs(d - 1.5 * (0.08 / 4000) * 16000) < 1e-9
    # a starved window (chip 10x slower) is capped by the anchor
    for _ in range(12):
        csp._note_device_wall(4000, 3.2)
    assert csp._deadline_for(4000) == 0.4  # anchor, not 1.5*3.2
    # disabled stall factor -> no deadline at all
    assert TPUCSP(stall_factor=None)._deadline_for(4000) is None


def test_sole_flush_deadline_is_absolute_budget():
    """A sole-flush consumer (the serial p99 path) gets an ABSOLUTE
    latency budget — deadline + estimated host-race stays inside
    ~420 ms even when a slow chip window inflates the EWMA past it —
    while the pipelined deadline keeps its anchor.  The race reserve
    uses the MEASURED host rate when one exists."""
    import fabric_tpu.csp.tpu.provider as prov

    with prov._host_rate_lock:
        saved = prov._host_rate_ewma[0]
        prov._host_rate_ewma[0] = None  # hint-only, deterministic
    try:
        csp = TPUCSP(stall_factor=1.0, host_rate_hint=9000.0)
        # slow window: ordinary flush wall 0.25s for 3000 lanes
        for _ in range(8):
            csp._note_device_wall(3000, 0.25)
        pipelined = csp._deadline_for(3000)
        assert pipelined == max(0.2, 3000 / 9000.0)  # anchor-capped
        sole = csp._sole_deadline_for(3000)
        assert sole is not None
        assert sole + 3000 / 9000.0 <= 0.421  # budget holds
        assert sole >= 0.05
        assert TPUCSP(stall_factor=None)._sole_deadline_for(3000) is None
        # a SLOWER measured host rate shrinks the deadline further
        prov._note_host_rate(3000, 0.5)  # 6000 sigs/s observed
        tighter = csp._sole_deadline_for(3000)
        assert tighter == 0.05  # 0.42 - 0.5 < floor
    finally:
        with prov._host_rate_lock:
            prov._host_rate_ewma[0] = saved


def test_flush_deadline_host_race_beats_stalled_device():
    """A device that never answers is beaten by the host race after the
    deadline; mask matches the host oracle exactly."""
    import threading

    from fabric_tpu.csp.tpu.provider import _FlushResult

    sw = SWCSP()
    items = _signed_items(12, sw)
    release = threading.Event()

    def stalled_collect():
        release.wait(10)
        return [True] * len(items)

    res = _FlushResult(
        [(stalled_collect, len(items))], len(items), sw=sw,
        device_items=items, deadline=0.05,
    )
    got = res.collect()
    release.set()
    assert got == sw.verify_batch(items)


def test_flush_race_yields_to_device_completion():
    """If the device finishes while the host race is mid-way, the device
    mask wins (no partial/mixed result)."""
    from fabric_tpu.csp.tpu.provider import _FlushResult

    sw = SWCSP()
    items = _signed_items(8, sw)
    res = _FlushResult(
        [(lambda: [True] * len(items), len(items))], len(items), sw=sw,
        device_items=items, deadline=0.01,
    )
    # seal via the waiter path first, as the background thread would
    res.start_background()
    got = res.collect()
    assert got == [True] * len(items)


def test_flush_waiter_failure_degrades_to_host():
    """A device collector that raises mid-flight leaves the host oracle
    answering for the whole flush."""
    from fabric_tpu.csp.tpu.provider import _FlushResult

    sw = SWCSP()
    items = _signed_items(10, sw)

    def broken_collect():
        raise RuntimeError("device lost")

    res = _FlushResult(
        [(broken_collect, len(items))], len(items), sw=sw,
        device_items=items,
    )
    assert res.collect() == sw.verify_batch(items)


def test_flush_collect_concurrent_segments_consistent():
    """Many threads collecting the same flush all see the one sealed
    mask (the r3 advisor's double-materialization race)."""
    import threading

    from fabric_tpu.csp.tpu.provider import _FlushResult

    sw = SWCSP()
    items = _signed_items(16, sw)
    calls = []

    def device_collect():
        calls.append(1)
        return sw.verify_batch(items)

    res = _FlushResult(
        [(device_collect, len(items))], len(items), sw=sw,
        device_items=items,
    )
    got: list = [None] * 6
    ths = [
        threading.Thread(target=lambda i=i: got.__setitem__(i, res.collect()))
        for i in range(6)
    ]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    want = sw.verify_batch(items)
    assert all(g == want for g in got)
    assert len(calls) == 1  # materialized exactly once
