"""Key-value store SPI + implementations.

Equivalent of the reference's common/ledger/util/leveldbhelper (a shared
goleveldb wrapper with db-name prefixing, batches and range iterators).
goleveldb has no Python counterpart in this image, so the durable backend
is sqlite (WAL mode, ordered BLOB keys give the same range-scan
contract); an in-memory impl serves tests and ephemeral ledgers.
"""

from __future__ import annotations

import bisect
import os
import sqlite3
import threading
from typing import Iterator

from fabric_tpu.devtools import faultline


class KVStore:
    """Ordered byte-key store. Iteration is over a half-open [start, end)
    range in lexicographic key order, like leveldb iterators."""

    def get(self, key: bytes) -> bytes | None:
        raise NotImplementedError

    def get_many(self, keys) -> dict[bytes, bytes]:
        """Present keys -> values (absent keys omitted).  Backends
        override with one round-trip; the default loops."""
        out = {}
        for k in keys:
            v = self.get(k)
            if v is not None:
                out[k] = v
        return out

    def write_batch(self, puts: dict[bytes, bytes], deletes=()) -> None:
        raise NotImplementedError

    def write_batch_if_absent(self, puts: dict[bytes, bytes]) -> None:
        """Insert keys that do not exist yet; existing keys keep their
        value (leveldb has no native merge operator either — the
        reference reads before writing for first-wins indexes; backends
        here do it in one INSERT OR IGNORE round-trip)."""
        existing = self.get_many(list(puts))
        self.write_batch({k: v for k, v in puts.items() if k not in existing})

    def put(self, key: bytes, value: bytes) -> None:
        self.write_batch({key: value})

    def delete(self, key: bytes) -> None:
        self.write_batch({}, [key])

    def iterate(self, start: bytes = b"", end: bytes | None = None) -> Iterator[tuple[bytes, bytes]]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemKVStore(KVStore):
    def __init__(self) -> None:
        self._data: dict[bytes, bytes] = {}
        self._keys: list[bytes] = []
        self._lock = threading.RLock()

    def get(self, key: bytes) -> bytes | None:
        with self._lock:
            return self._data.get(key)

    def write_batch(self, puts, deletes=()) -> None:
        with self._lock:
            for k, v in puts.items():
                if k not in self._data:
                    bisect.insort(self._keys, k)
                self._data[k] = v
            for k in deletes:
                if k in self._data:
                    del self._data[k]
                    i = bisect.bisect_left(self._keys, k)
                    if i < len(self._keys) and self._keys[i] == k:
                        self._keys.pop(i)

    def iterate(self, start: bytes = b"", end: bytes | None = None):
        with self._lock:
            i = bisect.bisect_left(self._keys, start)
            keys = []
            while i < len(self._keys):
                k = self._keys[i]
                if end is not None and k >= end:
                    break
                keys.append(k)
                i += 1
            snapshot = [(k, self._data[k]) for k in keys]
        yield from snapshot


_SQLITE_SYNC_LEVELS = ("OFF", "NORMAL", "FULL", "EXTRA")


def _sqlite_sync_level(override: str | None) -> str:
    """PRAGMA synchronous level: ctor override, else
    FABRIC_TPU_SQLITE_SYNC, else NORMAL — the default the chaos-commit
    crash matrix and faultfuzz campaigns run against (in WAL mode,
    NORMAL can lose the last transactions on POWER loss but never
    corrupts, and the block-file-first invariant makes lost KV txns
    replayable from the file scan; FULL/EXTRA trade throughput for
    power-loss durability, OFF is bench-sweep-only)."""
    raw = (
        override
        if override is not None
        else os.environ.get("FABRIC_TPU_SQLITE_SYNC", "")
    ).strip().upper()
    if not raw:
        return "NORMAL"
    if raw not in _SQLITE_SYNC_LEVELS:
        raise ValueError(
            f"FABRIC_TPU_SQLITE_SYNC={raw!r}: expected one of "
            f"{'/'.join(_SQLITE_SYNC_LEVELS)}"
        )
    return raw


def _sqlite_wal_checkpoint(override: int | None) -> int:
    """wal_autocheckpoint page threshold: ctor override, else
    FABRIC_TPU_WAL_CHECKPOINT, else sqlite's stock 1000.  Larger values
    move checkpoint I/O off the commit path at the cost of a longer WAL
    (recovery still replays it fully); 0 disables auto-checkpointing
    entirely (operator-driven checkpoints only)."""
    if override is not None:
        return max(0, int(override))
    raw = os.environ.get("FABRIC_TPU_WAL_CHECKPOINT", "").strip()
    if not raw:
        return 1000
    try:
        return max(0, int(raw))
    except ValueError:
        raise ValueError(
            f"FABRIC_TPU_WAL_CHECKPOINT={raw!r} is not an integer page "
            "count (0 disables auto-checkpointing)"
        ) from None


class SqliteKVStore(KVStore):
    """Durable backend. One table of BLOB key/value; WAL journaling gives
    atomic batch commits (the recovery property blkstorage/kvledger rely
    on, reference blockfile checkpoints + leveldb atomicity).

    Durability knobs (`python bench.py --sweep-sqlite` measures the
    combos; the chaos crash matrix pins the default's safety):
    `synchronous`/`FABRIC_TPU_SQLITE_SYNC` and
    `wal_autocheckpoint`/`FABRIC_TPU_WAL_CHECKPOINT` — see
    _sqlite_sync_level/_sqlite_wal_checkpoint."""

    def __init__(self, path: str, synchronous: str | None = None,
                 wal_autocheckpoint: int | None = None):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self.sync_level = _sqlite_sync_level(synchronous)
        self._conn.execute(f"PRAGMA synchronous={self.sync_level}")
        self.wal_autocheckpoint = _sqlite_wal_checkpoint(wal_autocheckpoint)
        self._conn.execute(
            f"PRAGMA wal_autocheckpoint={self.wal_autocheckpoint:d}"
        )
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB NOT NULL)"
        )
        self._conn.commit()
        self._lock = threading.RLock()

    def get(self, key: bytes) -> bytes | None:
        with self._lock:
            row = self._conn.execute("SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
        return None if row is None else row[0]

    def get_many(self, keys) -> dict[bytes, bytes]:
        keys = list(keys)
        out: dict[bytes, bytes] = {}
        with self._lock:
            for off in range(0, len(keys), 500):  # sqlite variable limit
                chunk = keys[off:off + 500]
                rows = self._conn.execute(
                    "SELECT k, v FROM kv WHERE k IN (%s)"
                    % ",".join("?" * len(chunk)),
                    chunk,
                ).fetchall()
                out.update(rows)
        return out

    def write_batch(self, puts, deletes=()) -> None:
        # fault point BEFORE the transaction: an injected crash here
        # models process death between the block-file fsync and the KV
        # txn (sqlite's own atomicity covers mid-txn death)
        faultline.point("kvstore.txn", puts=len(puts))
        with self._lock:
            with self._conn:
                self._conn.executemany(
                    "INSERT INTO kv(k, v) VALUES(?, ?) "
                    "ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                    [(k, v) for k, v in puts.items()],
                )
                self._conn.executemany(
                    "DELETE FROM kv WHERE k = ?", [(k,) for k in deletes]
                )

    def write_batch_if_absent(self, puts) -> None:
        # first occurrence wins WITHIN the batch too: sqlite executes
        # the rows in order and ignores every later conflicting insert
        with self._lock:
            with self._conn:
                self._conn.executemany(
                    "INSERT OR IGNORE INTO kv(k, v) VALUES(?, ?)",
                    list(puts.items()),
                )

    def iterate(self, start: bytes = b"", end: bytes | None = None):
        with self._lock:
            if end is None:
                rows = self._conn.execute(
                    "SELECT k, v FROM kv WHERE k >= ? ORDER BY k", (start,)
                ).fetchall()
            else:
                rows = self._conn.execute(
                    "SELECT k, v FROM kv WHERE k >= ? AND k < ? ORDER BY k",
                    (start, end),
                ).fetchall()
        yield from rows

    def close(self) -> None:
        self._conn.close()


class WriteBatchCollector(KVStore):
    """Buffers every mutation destined for `base` so one whole commit —
    state + history + pvt store + block index + savepoints — lands in a
    SINGLE base write_batch: on the sqlite backend that is exactly one
    transaction (the group-commit seam; the reference accumulates a
    leveldbhelper UpdateBatch per store but still pays one WriteBatch
    per store per block).  Reads are overlay-aware (read-your-writes),
    so MVCC validation of block k+1 in a group sees block k's buffered
    writes; flush() is all-or-nothing."""

    def __init__(self, base: KVStore):
        self._base = base
        self._puts: dict[bytes, bytes] = {}
        self._dels: set[bytes] = set()

    def get(self, key: bytes) -> bytes | None:
        if key in self._puts:
            return self._puts[key]
        if key in self._dels:
            return None
        return self._base.get(key)

    def get_many(self, keys) -> dict[bytes, bytes]:
        out: dict[bytes, bytes] = {}
        missing: list[bytes] = []
        for k in keys:
            if k in self._puts:
                out[k] = self._puts[k]
            elif k not in self._dels:
                missing.append(k)
        if missing:
            out.update(self._base.get_many(missing))
        return out

    def write_batch(self, puts, deletes=()) -> None:
        for k, v in puts.items():
            self._dels.discard(k)
            self._puts[k] = v
        for k in deletes:
            self._puts.pop(k, None)
            self._dels.add(k)

    # write_batch_if_absent: the KVStore default (get_many + filtered
    # write_batch) is already correct here because get_many sees the
    # overlay — first-wins holds across the buffered blocks of a group
    # as well as against committed state.

    def iterate(self, start: bytes = b"", end: bytes | None = None):
        """Merge the overlay into the base's ordered scan (the pvt
        store's expiry purge range-reads mid-commit)."""
        ov = iter(sorted(
            k for k in self._puts
            if k >= start and (end is None or k < end)
        ))
        ok = next(ov, None)
        for k, v in self._base.iterate(start, end):
            while ok is not None and ok < k:
                yield ok, self._puts[ok]
                ok = next(ov, None)
            if ok == k:
                yield k, self._puts[k]
                ok = next(ov, None)
                continue
            if k in self._dels:
                continue
            yield k, v
        while ok is not None:
            yield ok, self._puts[ok]
            ok = next(ov, None)

    @property
    def pending(self) -> int:
        return len(self._puts) + len(self._dels)

    def flush(self) -> None:
        """Commit everything buffered to the base store in one
        write_batch (one sqlite transaction), then reset."""
        if self._puts or self._dels:
            self._base.write_batch(self._puts, sorted(self._dels))
        self._puts = {}
        self._dels = set()

    def discard(self) -> None:
        """Drop everything buffered without touching the base store —
        the group-commit failure rollback."""
        self._puts = {}
        self._dels = set()


class NamedDB(KVStore):
    """A prefixed view over a shared store — the reference's
    leveldbhelper.Provider GetDBHandle(dbName) pattern."""

    _SEP = b"\x00\xff"

    def __init__(self, base: KVStore, name: str):
        self._base = base
        self._prefix = name.encode() + self._SEP

    def rebase(self, base: KVStore) -> "NamedDB":
        """The same namespace view over a different base — how commit
        hands each store a WriteBatchCollector without re-deriving the
        prefix from a name."""
        c = NamedDB.__new__(NamedDB)
        c._base = base
        c._prefix = self._prefix
        return c

    def _k(self, key: bytes) -> bytes:
        return self._prefix + key

    def get(self, key: bytes) -> bytes | None:
        return self._base.get(self._k(key))

    def get_many(self, keys) -> dict[bytes, bytes]:
        plen = len(self._prefix)
        got = self._base.get_many([self._k(k) for k in keys])
        return {k[plen:]: v for k, v in got.items()}

    def write_batch(self, puts, deletes=()) -> None:
        self._base.write_batch(
            {self._k(k): v for k, v in puts.items()}, [self._k(k) for k in deletes]
        )

    def write_batch_if_absent(self, puts) -> None:
        self._base.write_batch_if_absent(
            {self._k(k): v for k, v in puts.items()}
        )

    def iterate(self, start: bytes = b"", end: bytes | None = None):
        pend = self._prefix + end if end is not None else _prefix_end(self._prefix)
        for k, v in self._base.iterate(self._prefix + start, pend):
            yield k[len(self._prefix):], v


def _prefix_end(prefix: bytes) -> bytes | None:
    """Smallest key greater than every key with this prefix."""
    p = bytearray(prefix)
    while p:
        if p[-1] != 0xFF:
            p[-1] += 1
            return bytes(p)
        p.pop()
    return None


def wipe_prefix(store: KVStore, prefix: bytes) -> int:
    """Delete every key under `prefix` in one batch; returns the count.
    THE range-delete helper — ledger admin repair ops and the crashed-
    import discard both sweep namespaces through it, so the 0xFF-carry
    end-key logic lives in exactly one place."""
    keys = [k for k, _ in store.iterate(prefix, _prefix_end(prefix))]
    if keys:
        store.write_batch({}, deletes=keys)
    return len(keys)


def open_kvstore(path: str | None) -> KVStore:
    """None/':memory:' -> MemKVStore, else sqlite at path."""
    if path in (None, ":memory:"):
        return MemKVStore()
    return SqliteKVStore(path)


__all__ = [
    "KVStore",
    "MemKVStore",
    "SqliteKVStore",
    "NamedDB",
    "WriteBatchCollector",
    "open_kvstore",
    "wipe_prefix",
]
