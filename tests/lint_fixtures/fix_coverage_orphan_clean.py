"""Clean twin of fix_coverage_orphan_dirty: the plan rule pins the
seam by its exact name, so the seam is armable and the rule is not an
orphan — chaos-coverage stays quiet."""

from fabric_tpu.devtools import faultline

RELAY_PLAN = {
    "seed": 3,
    "faults": [
        {"point": "relay.send", "action": "raise", "error": "OSError"},
    ],
}


def forward(batch):
    faultline.point("relay.send", n=len(batch))
    return list(batch)
