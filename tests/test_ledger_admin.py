"""Ledger repair ops (peer node rebuild-dbs / rollback / reset), rich
JSON-selector queries, filtered-block deliver, and the caching MSP."""

import json

import pytest

from fabric_tpu.ledger import LedgerProvider
from fabric_tpu.ledger import admin
from fabric_tpu.ledger.richquery import execute_query, match_selector


# -- rich queries ----------------------------------------------------------


class TestRichQuery:
    def test_selectors(self):
        doc = {"color": "red", "size": 5, "owner": {"org": "Org1"}}
        assert match_selector(doc, {"color": "red"})
        assert not match_selector(doc, {"color": "blue"})
        assert match_selector(doc, {"size": {"$gt": 3, "$lte": 5}})
        assert match_selector(doc, {"owner.org": "Org1"})
        assert match_selector(doc, {"color": {"$in": ["red", "blue"]}})
        assert match_selector(doc, {"weight": {"$exists": False}})
        assert not match_selector(doc, {"size": {"$ne": 5}})
        assert match_selector(
            doc, {"$or": [{"color": "blue"}, {"size": {"$gte": 5}}]}
        )

    def test_execute_query_scan(self):
        pairs = [
            ("a1", json.dumps({"t": "car", "price": 10}).encode()),
            ("a2", json.dumps({"t": "car", "price": 30}).encode()),
            ("a3", json.dumps({"t": "boat", "price": 30}).encode()),
            ("a4", b"not-json"),
        ]
        q = json.dumps({"selector": {"t": "car", "price": {"$gt": 5}}})
        assert [k for k, _ in execute_query(pairs, q)] == ["a1", "a2"]
        q = json.dumps({"selector": {"price": {"$gte": 10}}, "limit": 2})
        assert len(execute_query(pairs, q)) == 2

    def test_simulator_get_query_result(self):
        from fabric_tpu.ledger.kvstore import MemKVStore
        from fabric_tpu.ledger.statedb import Height, VersionedDB, VersionedValue
        from fabric_tpu.ledger.txmgmt import TxSimulator

        db = VersionedDB(MemKVStore())
        db.apply_updates(
            {
                "cc": {
                    "m1": VersionedValue(
                        json.dumps({"make": "tesla"}).encode(), Height(1, 0)
                    ),
                    "m2": VersionedValue(
                        json.dumps({"make": "ford"}).encode(), Height(1, 1)
                    ),
                }
            },
            Height(1, 2),
        )
        sim = TxSimulator(db)
        rows = sim.get_query_result(
            "cc", json.dumps({"selector": {"make": "tesla"}})
        )
        assert [k for k, _ in rows] == ["m1"]


# -- repair ops ------------------------------------------------------------


def _make_chain(tmp_path, n_blocks=3):
    """A committed chain via the devnode-free path: genesis + n blocks."""
    from orgfix import make_org
    from fabric_tpu.common import configtx_builder as ctx
    from fabric_tpu.msp import msp_config_from_ca
    from fabric_tpu.node.devnode import DevNode

    org = make_org("Org1MSP")
    oorg = make_org("OrdererMSP")
    app = ctx.application_group(
        {"Org1": ctx.org_group("Org1MSP", msp_config_from_ca(org.ca, "Org1MSP"))}
    )
    ordg = ctx.orderer_group(
        {"O": ctx.org_group("OrdererMSP", msp_config_from_ca(oorg.ca, "OrdererMSP"))},
        consensus_type="solo",
        max_message_count=1,
    )
    genesis = ctx.genesis_block("repairch", ctx.channel_group(app, ordg))
    peer = org.signer("peer0", role_ou="peer")
    client = org.signer("user", role_ou="client")

    def kv(sim, args):
        sim.set_state("kv", args[0].decode(), args[1])
        return 200, "", b""

    node = DevNode(
        genesis, root_dir=str(tmp_path), csp=org.csp, peer_signer=peer,
        chaincodes={"kv": kv}, batch_timeout_s=0.05,
    )
    from fabric_tpu import protoutil
    from fabric_tpu.protos.peer import proposal_pb2

    for i in range(n_blocks):
        prop, _ = protoutil.create_chaincode_proposal(
            client.serialize(), "repairch", "kv",
            [b"k%d" % i, b"v%d" % i],
        )
        signed = proposal_pb2.SignedProposal(
            proposal_bytes=prop.SerializeToString(),
            signature=client.sign(prop.SerializeToString()),
        )
        resp = node.endorser.process_proposal(signed)
        env = protoutil.create_signed_tx(prop, client, [resp])
        node.broadcast(env)
        node.wait_commit()
    node.shutdown()
    node.provider.close()
    return "repairch"


def test_rebuild_dbs_replays_state(tmp_path):
    lid = _make_chain(tmp_path, 3)
    assert admin.rebuild_dbs(str(tmp_path)) == [lid]
    provider = LedgerProvider(str(tmp_path))
    ledger = provider.open(lid)
    assert ledger.height == 4
    assert ledger.get_state("kv", "k2") == b"v2"
    provider.close()


def test_rollback_truncates_and_replays(tmp_path):
    lid = _make_chain(tmp_path, 3)
    assert admin.rollback(str(tmp_path), lid, 2) == 3
    provider = LedgerProvider(str(tmp_path))
    ledger = provider.open(lid)
    assert ledger.height == 3
    assert ledger.get_state("kv", "k1") == b"v1"
    assert ledger.get_state("kv", "k2") is None  # rolled off
    provider.close()


def test_reset_to_genesis(tmp_path):
    lid = _make_chain(tmp_path, 2)
    assert admin.reset(str(tmp_path)) == {lid: 1}
    provider = LedgerProvider(str(tmp_path))
    ledger = provider.open(lid)
    assert ledger.height == 1
    assert ledger.get_state("kv", "k0") is None
    provider.close()


# -- filtered blocks -------------------------------------------------------


def test_filter_block(tmp_path):
    lid = _make_chain(tmp_path, 1)
    provider = LedgerProvider(str(tmp_path))
    ledger = provider.open(lid)
    blk = ledger.get_block_by_number(1)
    from fabric_tpu.common.deliver import filter_block
    from fabric_tpu.protos.peer import transaction_pb2 as V

    fb = filter_block(blk)
    assert fb.number == 1 and fb.channel_id == "repairch"
    assert len(fb.filtered_transactions) == 1
    ftx = fb.filtered_transactions[0]
    assert ftx.txid and ftx.tx_validation_code == V.VALID
    # no payloads/rwsets travel in a filtered block
    assert len(fb.SerializeToString()) < len(blk.SerializeToString()) / 4
    provider.close()


# -- MSP cache -------------------------------------------------------------

def test_cached_msp_memoizes():
    from orgfix import make_org
    from fabric_tpu.msp.cache import CachedMSP

    org = make_org("Org1MSP")
    signer = org.signer("peer0")
    raw = signer.serialize()

    calls = {"de": 0, "val": 0}

    class Spy:
        def deserialize_identity(self, s):
            calls["de"] += 1
            return org.msp.deserialize_identity(s)

        def validate(self, ident):
            calls["val"] += 1
            return org.msp.validate(ident)

    cached = CachedMSP(Spy())
    i1 = cached.deserialize_identity(raw)
    i2 = cached.deserialize_identity(raw)
    assert calls["de"] == 1 and i1 is i2
    cached.validate(i1)
    cached.validate(i2)
    assert calls["val"] == 1


def test_pause_resume_and_upgrade_dbs(tmp_path):
    """pause/resume markers + data-format stamp (reference
    internal/peer/node/{pause,resume,upgrade_dbs}.go)."""
    from fabric_tpu.ledger import admin

    root = str(tmp_path / "peer")
    import os

    os.makedirs(root)
    # seed a dummy index store via pause itself
    admin.pause(root, "ch1")
    admin.pause(root, "ch2")
    assert admin.paused_channels(root) == {"ch1", "ch2"}
    admin.resume(root, "ch1")
    assert admin.paused_channels(root) == {"ch2"}
    # upgrade stamps the format; second run is a no-op
    admin.upgrade_dbs(root)
    assert admin.upgrade_dbs(root) == []


class TestIndexedQueryParity:
    """Indexed execution must never under-select vs the full scan
    (advisor round-2 high finding): non-scalar operands and bool/number
    cross-type matches (True == 1 under Python ==, different index type
    tags) have to fall back or probe both encodings."""

    def _db(self, docs):
        from fabric_tpu.ledger.kvstore import MemKVStore
        from fabric_tpu.ledger.statedb import Height, VersionedDB, VersionedValue

        db = VersionedDB(MemKVStore())
        db.apply_updates(
            {
                "cc": {
                    k: VersionedValue(json.dumps(d).encode(), Height(1, i))
                    for i, (k, d) in enumerate(docs.items())
                }
            },
            Height(1, len(docs)),
        )
        return db

    def _both(self, db, selector, **extra):
        from fabric_tpu.ledger.richquery import execute_query_indexed

        q = json.dumps({"selector": selector, **extra})
        scan = [
            k
            for k, _ in execute_query(
                ((k, vv.value) for k, vv in db.get_state_range("cc", "", "")), q
            )
        ]
        indexed = execute_query_indexed(db, "cc", q)
        return scan, indexed

    def test_nonscalar_eq_falls_back_to_scan(self):
        db = self._db({"d1": {"tags": ["a", "b"]}, "d2": {"tags": "x"}})
        db.define_index("cc", "tags")
        scan, indexed = self._both(db, {"tags": ["a", "b"]})
        assert scan == ["d1"]
        assert indexed is None  # planner must decline, not return []

    def test_bool_number_cross_type_eq(self):
        db = self._db(
            {"b1": {"flag": True}, "n1": {"flag": 1}, "z": {"flag": 0},
             "b0": {"flag": False}, "n2": {"flag": 2}}
        )
        db.define_index("cc", "flag")
        for sel, want in [
            ({"flag": 1}, ["b1", "n1"]),      # 1 == True
            ({"flag": True}, ["b1", "n1"]),
            ({"flag": 0}, ["b0", "z"]),
            ({"flag": False}, ["b0", "z"]),
            ({"flag": 2}, ["n2"]),
            ({"flag": {"$in": [True, 2]}}, ["b1", "n1", "n2"]),
        ]:
            scan, indexed = self._both(db, sel)
            assert scan == want
            assert indexed is not None and [k for k, _, _ in indexed] == want

    def test_numeric_range_includes_bool_docs(self):
        db = self._db(
            {"b1": {"v": True}, "n1": {"v": 5}, "n0": {"v": -3}}
        )
        db.define_index("cc", "v")
        scan, indexed = self._both(db, {"v": {"$gte": 0}})
        assert scan == ["b1", "n1"]
        assert indexed is not None and [k for k, _, _ in indexed] == scan

    def test_bool_range_bound_falls_back(self):
        db = self._db({"n1": {"v": 5}, "b1": {"v": True}})
        db.define_index("cc", "v")
        scan, indexed = self._both(db, {"v": {"$gte": True}})
        assert indexed is None or [k for k, _, _ in indexed] == scan

    def test_unencodable_in_member_falls_back(self):
        db = self._db({"d1": {"v": [1, 2]}, "d2": {"v": "s"}})
        db.define_index("cc", "v")
        scan, indexed = self._both(db, {"v": {"$in": [[1, 2], "s"]}})
        assert scan == ["d1", "d2"]
        assert indexed is None

    def test_negative_zero_eq_and_range(self):
        db = self._db({"neg0": {"v": -0.0}, "pos0": {"v": 0}})
        db.define_index("cc", "v")
        for sel in ({"v": 0}, {"v": {"$gte": 0}}, {"v": {"$gte": -1, "$lte": 1}}):
            scan, indexed = self._both(db, sel)
            assert scan == ["neg0", "pos0"]
            assert indexed is not None and [k for k, _, _ in indexed] == scan

    def test_bool_sweep_gated_outside_01(self):
        db = self._db({"b1": {"v": True}, "n1": {"v": 500}})
        db.define_index("cc", "v")
        scan, indexed = self._both(db, {"v": {"$gte": 100}})
        assert scan == ["n1"]
        assert indexed is not None and [k for k, _, _ in indexed] == scan
