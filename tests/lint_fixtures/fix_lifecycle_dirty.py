"""Seeded violation: a run-until-stopped service thread whose handle
is discarded and whose loop never blocks on a stop signal — nothing
can ever stop or join it (thread-lifecycle)."""

from fabric_tpu.devtools.lockwatch import spawn_thread


def emit():
    return None


class Beacon:
    def __init__(self):
        self._running = True

    def start(self):
        spawn_thread(  # <- thread-lifecycle fires HERE
            target=self._loop, name="beacon", kind="service",
        ).start()

    def _loop(self):
        while self._running:
            emit()
