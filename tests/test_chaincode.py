"""Chaincode runtime tests: shim <-> support stream state machine,
in-process and external-process execution, range queries, cc2cc, error
paths (reference core/chaincode/chaincode_support_test.go strategy:
real handler + in-proc streams)."""

import subprocess
import sys
import textwrap
import time

import pytest

from fabric_tpu.chaincode import Chaincode, ChaincodeSupport, InProcStream
from fabric_tpu.chaincode.shim import error, success
from fabric_tpu.chaincode.support import ChaincodeExecuteError, TCPChaincodeListener
from fabric_tpu.ledger.kvstore import MemKVStore
from fabric_tpu.ledger.statedb import Height, VersionedDB, VersionedValue
from fabric_tpu.ledger.txmgmt import TxSimulator


class KVChaincode(Chaincode):
    def invoke(self, stub):
        fn, params = stub.get_function_and_parameters()
        if fn == "put":
            stub.put_state(params[0].decode(), params[1])
            return success()
        if fn == "get":
            return success(stub.get_state(params[0].decode()))
        if fn == "del":
            stub.del_state(params[0].decode())
            return success()
        if fn == "range":
            items = [
                f"{k}={v.decode()}"
                for k, v in stub.get_state_by_range(
                    params[0].decode(), params[1].decode()
                )
            ]
            return success(",".join(items).encode())
        if fn == "call":  # cc2cc
            resp = stub.invoke_chaincode(params[0].decode(), list(params[1:]))
            return resp
        if fn == "boom":
            raise RuntimeError("chaincode exploded")
        if fn == "event":
            stub.set_event("my-event", b"event-payload")
            return success()
        return error(f"unknown function {fn!r}")


@pytest.fixture
def support():
    return ChaincodeSupport(invoke_timeout_s=5.0)


@pytest.fixture
def sim():
    return TxSimulator(VersionedDB(MemKVStore()))


def _launch(support, name="kvcc", cc=None):
    stream = InProcStream(support, cc or KVChaincode(), name)
    stream.start()
    stream.wait_registered(support, name)
    return stream


def test_execute_put_get(support, sim):
    _launch(support)
    resp, _ = support.execute("kvcc", "ch", "tx1", sim, [b"put", b"k1", b"v1"])
    assert resp.status == 200
    resp, _ = support.execute("kvcc", "ch", "tx2", sim, [b"get", b"k1"])
    # within the same simulator, reads see prior writes
    assert resp.status == 200 and resp.payload == b"v1"
    # rwset namespaced to the chaincode name
    results = sim.get_tx_simulation_results()
    assert b"kvcc" in results


def test_execute_unregistered_chaincode(support, sim):
    with pytest.raises(ChaincodeExecuteError, match="not registered"):
        support.execute("ghost", "ch", "tx1", sim, [b"get", b"x"])


def test_chaincode_exception_becomes_error(support, sim):
    _launch(support)
    with pytest.raises(ChaincodeExecuteError, match="exploded"):
        support.execute("kvcc", "ch", "tx1", sim, [b"boom"])


def test_range_query_pagination(support):
    db = VersionedDB(MemKVStore())
    db.apply_updates(
        {
            "kvcc": {
                f"k{i:04d}": VersionedValue(b"v%d" % i, Height(1, i), b"")
                for i in range(250)  # 2.5 pages at page size 100
            }
        },
        Height(1, 249),
    )
    sim = TxSimulator(db)
    _launch(support)
    resp, _ = support.execute(
        "kvcc", "ch", "tx-range", sim, [b"range", b"k0000", b"k0250"]
    )
    assert resp.status == 200
    items = resp.payload.decode().split(",")
    assert len(items) == 250
    assert items[0] == "k0000=v0" and items[-1] == "k0249=v249"


def test_cc2cc_shares_simulator(support, sim):
    _launch(support, "kvcc")
    _launch(support, "othercc")
    resp, _ = support.execute(
        "kvcc", "ch", "tx1", sim, [b"call", b"othercc", b"put", b"shared", b"yes"]
    )
    assert resp.status == 200
    results = sim.get_tx_simulation_results()
    assert b"othercc" in results  # write landed in callee's namespace


def test_chaincode_event_propagates(support, sim):
    _launch(support)
    resp, event = support.execute("kvcc", "ch", "tx-ev", sim, [b"event"])
    assert resp.status == 200
    from fabric_tpu.protos.peer import chaincode_event_pb2

    ev = chaincode_event_pb2.ChaincodeEvent.FromString(event)
    assert ev.event_name == "my-event" and ev.payload == b"event-payload"


EXTERNAL_CC = textwrap.dedent(
    """
    import sys
    sys.path.insert(0, %r)
    from fabric_tpu.chaincode.shim import Chaincode, shim_main, success

    class Echo(Chaincode):
        def invoke(self, stub):
            stub.put_state("echo", b"-".join(stub.args))
            return success(b"-".join(stub.args))

    shim_main(Echo(), sys.argv[2], sys.argv[1], auth_token=sys.argv[3])
    """
)


def test_external_process_chaincode(support, sim, tmp_path):
    """The externalbuilder path: chaincode as a separate OS process
    connecting back over TCP (reference core/container/externalbuilder),
    presenting its launch credential in the listener handshake."""
    import os

    listener = TCPChaincodeListener(support)
    token = support.issue_launch_token("echocc")
    script = tmp_path / "echo_cc.py"
    script.write_text(EXTERNAL_CC % os.getcwd())
    proc = subprocess.Popen(
        [sys.executable, str(script), f"127.0.0.1:{listener.addr[1]}",
         "echocc", token],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
    )
    try:
        deadline = time.monotonic() + 10
        while not support.registered("echocc"):
            if time.monotonic() > deadline:
                raise AssertionError(
                    "external chaincode did not register: "
                    + proc.stderr.peek().decode("utf-8", "replace")
                )
            time.sleep(0.05)
        resp, _ = support.execute("echocc", "ch", "xtx", sim, [b"a", b"b"])
        assert resp.status == 200 and resp.payload == b"a-b"
        assert sim.get_state("echocc", "echo") == b"a-b"
    finally:
        proc.kill()
        listener.close()


def test_rich_query_via_shim(support, sim):
    """GetQueryResult: JSON selector over namespace state (reference shim
    GetQueryResult backed by the CouchDB-style rich query engine)."""
    import json

    from fabric_tpu.ledger.statedb import Height, VersionedValue

    class RichCC(Chaincode):
        def invoke(self, stub):
            op, params = stub.get_function_and_parameters()
            if op == "query":
                rows = list(stub.get_query_result(params[0].decode()))
                return success(json.dumps([k for k, _ in rows]).encode())
            return error("bad op")

    # rich queries read COMMITTED state only (the reference's couchdb
    # semantics: a tx's own pending writes are not visible to queries)
    sim._db.apply_updates(
        {
            "richcc": {
                f"doc{i}": VersionedValue(
                    json.dumps({"type": "t%d" % (i % 2), "n": i}).encode(),
                    Height(1, i),
                )
                for i in range(4)
            }
        },
        Height(1, 4),
    )
    _launch(support, "richcc", RichCC())
    q = json.dumps({"selector": {"type": "t1", "n": {"$gt": 1}}}).encode()
    resp, _ = support.execute("richcc", "ch", "rq2", sim, [b"query", q])
    assert resp.status == 200
    assert json.loads(resp.payload) == ["doc3"]


def test_rogue_process_cannot_register(support):
    """Chaincode-connection access control (reference
    core/chaincode/accesscontrol/access_control.go): a local process
    WITHOUT a peer-issued launch credential never registers — neither
    bare protocol (no handshake), nor a guessed token, nor a VALID
    credential presented for a different chaincode's name."""
    import socket as socketlib
    import struct

    from fabric_tpu.protos.peer import chaincode_pb2
    from fabric_tpu.protos.peer import chaincode_shim_pb2 as shim_pb

    LEN = struct.Struct(">I")
    M = shim_pb.ChaincodeMessage
    listener = TCPChaincodeListener(support)
    support.issue_launch_token("legitcc")

    def attempt(frames):
        sock = socketlib.create_connection(("127.0.0.1", listener.addr[1]))
        try:
            for f in frames:
                sock.sendall(LEN.pack(len(f)) + f)
            sock.settimeout(2.0)
            got = b""
            try:
                while len(got) < 4:
                    chunk = sock.recv(4096)
                    if not chunk:
                        return None  # closed without an answer
                    got += chunk
            except TimeoutError:
                return None
            except ConnectionResetError:
                # the peer dropped us with frames still unread, so the
                # kernel answered RST instead of FIN — still "closed
                # without an answer" (which close the server wins is a
                # race; both spellings are the same refusal)
                return None
            (ln,) = LEN.unpack_from(got)
            while len(got) < 4 + ln:
                got += sock.recv(4096)
            return M.FromString(got[4:4 + ln])
        finally:
            sock.close()

    reg = M(
        type=M.REGISTER,
        payload=chaincode_pb2.ChaincodeID(name="legitcc").SerializeToString(),
    ).SerializeToString()

    # 1) no handshake at all: dropped before the protocol starts
    assert attempt([reg]) is None
    assert not support.registered("legitcc")
    # 2) forged token: dropped
    bad = b"\x00".join([b"CCAUTH1", b"legitcc", b"00" * 32])
    assert attempt([bad, reg]) is None
    assert not support.registered("legitcc")
    # 3) valid token for another name: REGISTER name mismatch -> ERROR
    other_token = support.issue_launch_token("othercc")
    hello = b"\x00".join([b"CCAUTH1", b"othercc", other_token.encode()])
    resp = attempt([hello, reg])
    assert resp is not None and resp.type == M.ERROR
    assert not support.registered("legitcc")
    # 4) the real credential works end to end
    tok = support.issue_launch_token("legitcc")
    hello = b"\x00".join([b"CCAUTH1", b"legitcc", tok.encode()])
    resp = attempt([hello, reg])
    assert resp is not None and resp.type == M.REGISTERED
    listener.close()
