"""Idemix presentation signatures (reference idemix/signature.go).

A signature proves, in zero knowledge: "I hold a credential (A, B, e, s)
from this issuer over attributes (m_1..m_L) and secret key sk; I disclose
the attributes in D and hide the rest; Nym is a pseudonym bound to the same
sk" — and signs a message via Fiat-Shamir.

Construction (re-derived from the CDL scheme the reference implements; see
signature.go NewSignature for the reference's randomization with
r1/r2/r3 and the same APrime/ABar/BPrime triple):

    r1 <- Zr*, r3 = 1/r1, r2 <- Zr
    APrime = A^r1
    ABar   = B^r1 * APrime^{-e}        # equals APrime^x
    BPrime = B^r1 * HRand^{-r2}
    s'     = s - r2 * r3

which gives the verifier-checkable identities

    e(APrime, W) == e(ABar, g2)                       (pairing check)
    ABar * BPrime^{-1} == APrime^{-e} * HRand^{r2}    (relation 1)
    g1^{-1} * prod_{i in D} HAttrs_i^{-m_i}
        == HSk^{sk} * HRand^{s'} * prod_{i in H} HAttrs_i^{m_i}
           * BPrime^{-r3}                             (relation 2)
    Nym == HSk^{sk} * HRand^{r_nym}                   (relation 3)

Relations 1-3 are proven with the generalized Schnorr engine
(fabric_tpu/idemix/schnorr.py); sk is shared between relations 2 and 3,
binding the pseudonym to the credential.

Batched verification (`verify_batch`): all N pairing checks against one
issuer key collapse — with random weights t_i — into TWO pairings:

    e(sum_i t_i * APrime_i, W) * e(-sum_i t_i * ABar_i, g2) == 1

This is the BN256 batch-verify baseline configuration (BASELINE.md): the
reference spends two FP256BN.Ate calls per signature
(signature.go:290-291); the batch spends two per *block*.
"""

from __future__ import annotations

import dataclasses

from fabric_tpu.idemix import bn254 as bn
from fabric_tpu.idemix import schnorr
from fabric_tpu.idemix.credential import Credential
from fabric_tpu.idemix.issuer import IssuerPublicKey


@dataclasses.dataclass
class Signature:
    a_prime: tuple
    a_bar: tuple
    b_prime: tuple
    nym: tuple
    challenge: int
    responses: dict[str, int]
    disclosure: list[bool]
    disclosed_attrs: dict[int, int]  # index -> scalar value
    nonce: bytes

    def to_bytes(self) -> bytes:
        import json

        return json.dumps(
            {
                "a_prime": bn.g1_to_bytes(self.a_prime).hex(),
                "a_bar": bn.g1_to_bytes(self.a_bar).hex(),
                "b_prime": bn.g1_to_bytes(self.b_prime).hex(),
                "nym": bn.g1_to_bytes(self.nym).hex(),
                "challenge": self.challenge,
                "responses": self.responses,
                "disclosure": self.disclosure,
                "disclosed_attrs": {
                    str(k): v for k, v in self.disclosed_attrs.items()
                },
                "nonce": self.nonce.hex(),
            },
            sort_keys=True,
        ).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "Signature":
        import json

        d = json.loads(raw)
        return cls(
            a_prime=bn.g1_from_bytes(bytes.fromhex(d["a_prime"])),
            a_bar=bn.g1_from_bytes(bytes.fromhex(d["a_bar"])),
            b_prime=bn.g1_from_bytes(bytes.fromhex(d["b_prime"])),
            nym=bn.g1_from_bytes(bytes.fromhex(d["nym"])),
            challenge=int(d["challenge"]),
            responses={k: int(v) for k, v in d["responses"].items()},
            disclosure=[bool(b) for b in d["disclosure"]],
            disclosed_attrs={
                int(k): int(v) for k, v in d["disclosed_attrs"].items()
            },
            nonce=bytes.fromhex(d["nonce"]),
        )


def _relations(
    ipk: IssuerPublicKey,
    a_prime,
    a_bar,
    b_prime,
    nym,
    disclosure: list[bool],
    disclosed_attrs: dict[int, int],
) -> list[schnorr.Relation]:
    hidden = [i for i, d in enumerate(disclosure) if not d]
    y1 = bn.g1_add(a_bar, bn.g1_neg(b_prime))
    rel1 = schnorr.Relation(
        target=y1, bases=[a_prime, ipk.h_rand], names=["neg_e", "r2"]
    )
    y2 = bn.g1_neg(bn.G1_GEN)
    for i, d in enumerate(disclosure):
        if d:
            y2 = bn.g1_add(
                y2,
                bn.g1_mul(ipk.h_attrs[i], (-disclosed_attrs[i]) % bn.R),
            )
    rel2 = schnorr.Relation(
        target=y2,
        bases=[ipk.h_sk, ipk.h_rand, *[ipk.h_attrs[i] for i in hidden],
               b_prime],
        names=["sk", "sprime", *[f"m_{i}" for i in hidden], "neg_r3"],
    )
    rel3 = schnorr.Relation(
        target=nym, bases=[ipk.h_sk, ipk.h_rand], names=["sk", "r_nym"]
    )
    return [rel1, rel2, rel3]


def _challenge_bytes(
    ipk: IssuerPublicKey,
    commitments,
    a_prime,
    a_bar,
    b_prime,
    nym,
    disclosure,
    disclosed_attrs,
    msg: bytes,
    nonce: bytes,
) -> int:
    chunks = [b"idemix-signature"]
    chunks += [bn.g1_to_bytes(t) for t in commitments]
    chunks += [
        bn.g1_to_bytes(a_prime),
        bn.g1_to_bytes(a_bar),
        bn.g1_to_bytes(b_prime),
        bn.g1_to_bytes(nym),
        ipk.hash(),
        bytes(disclosure),
        b"".join(
            i.to_bytes(4, "big") + v.to_bytes(32, "big")
            for i, v in sorted(disclosed_attrs.items())
        ),
        msg,
        nonce,
    ]
    return bn.hash_to_zr(*chunks)


def make_nym(sk: int, ipk: IssuerPublicKey, rng=None) -> tuple[tuple, int]:
    """(Nym, r_nym) — a fresh pseudonym commitment to sk (reference
    idemix/util.go MakeNym)."""
    r_nym = bn.rand_zr(rng)
    nym = bn.g1_add(bn.g1_mul(ipk.h_sk, sk), bn.g1_mul(ipk.h_rand, r_nym))
    return nym, r_nym


def new_signature(
    cred: Credential,
    sk: int,
    ipk: IssuerPublicKey,
    msg: bytes,
    disclosure: list[bool] | None = None,
    nonce: bytes = b"",
    nym: tuple | None = None,
    r_nym: int | None = None,
    rng=None,
) -> Signature:
    n_attrs = len(ipk.attr_names)
    if disclosure is None:
        disclosure = [False] * n_attrs
    if len(disclosure) != n_attrs or len(cred.attrs) != n_attrs:
        raise ValueError("disclosure/attribute length mismatch")
    if (nym is None) != (r_nym is None):
        raise ValueError("nym and r_nym must be supplied together")

    r1 = bn.rand_zr(rng)
    r2 = bn.rand_zr(rng)
    r3 = pow(r1, -1, bn.R)
    if nym is None:
        nym, r_nym = make_nym(sk, ipk, rng)

    a_prime = bn.g1_mul(cred.a, r1)
    b_r1 = bn.g1_mul(cred.b, r1)
    a_bar = bn.g1_add(b_r1, bn.g1_mul(a_prime, (-cred.e) % bn.R))
    b_prime = bn.g1_add(b_r1, bn.g1_mul(ipk.h_rand, (-r2) % bn.R))
    sprime = (cred.s - r2 * r3) % bn.R

    disclosed_attrs = {
        i: cred.attrs[i] for i, d in enumerate(disclosure) if d
    }
    hidden = [i for i, d in enumerate(disclosure) if not d]
    secrets = {
        "neg_e": (-cred.e) % bn.R,
        "r2": r2,
        "sk": sk,
        "sprime": sprime,
        "neg_r3": (-r3) % bn.R,
        "r_nym": r_nym,
    }
    for i in hidden:
        secrets[f"m_{i}"] = cred.attrs[i]

    rels = _relations(
        ipk, a_prime, a_bar, b_prime, nym, disclosure, disclosed_attrs
    )
    c, responses = schnorr.prove(
        rels,
        secrets,
        lambda ts: _challenge_bytes(
            ipk, ts, a_prime, a_bar, b_prime, nym, disclosure,
            disclosed_attrs, msg, nonce,
        ),
        rng=rng,
    )
    return Signature(
        a_prime=a_prime,
        a_bar=a_bar,
        b_prime=b_prime,
        nym=nym,
        challenge=c,
        responses=responses,
        disclosure=list(disclosure),
        disclosed_attrs=disclosed_attrs,
        nonce=nonce,
    )


def _check_schnorr(sig: Signature, ipk: IssuerPublicKey, msg: bytes) -> bool:
    """The host-side (non-pairing) part of verification.  Every field of
    `sig` is attacker-controlled: any malformed content (missing
    responses, out-of-range disclosed attrs, wrong shapes) must yield
    False, never an exception."""
    try:
        if sig.a_prime is None:
            return False
        for pt in (sig.a_prime, sig.a_bar, sig.b_prime, sig.nym):
            if pt is None or not bn.g1_is_on_curve(pt):
                return False
        rels = _relations(
            ipk, sig.a_prime, sig.a_bar, sig.b_prime, sig.nym,
            sig.disclosure, sig.disclosed_attrs,
        )
        commitments = schnorr.recompute_commitments(
            rels, sig.challenge, sig.responses
        )
        c = _challenge_bytes(
            ipk, commitments, sig.a_prime, sig.a_bar, sig.b_prime, sig.nym,
            sig.disclosure, sig.disclosed_attrs, msg, sig.nonce,
        )
        return c == sig.challenge
    except (ValueError, IndexError, KeyError, TypeError, OverflowError,
            AttributeError):
        return False


def verify(sig: Signature, ipk: IssuerPublicKey, msg: bytes) -> bool:
    """Single-signature verification (reference signature.go Ver: Schnorr
    recomputation then two Ate pairings at :290-291)."""
    if not _check_schnorr(sig, ipk, msg):
        return False
    return bn.pairing_check(
        [(sig.a_prime, ipk.w), (bn.g1_neg(sig.a_bar), bn.G2_GEN)]
    )


def verify_batch(
    sigs: list[Signature],
    ipk: IssuerPublicKey,
    msgs: list[bytes],
    rng=None,
) -> list[bool]:
    """Batched verification against one issuer key.

    Per-item Schnorr checks run first (cheap, host); surviving items enter
    the combined two-pairing check with random weights.  If the combined
    check fails, fall back to per-item pairing checks so the result is a
    per-signature mask — matching the CSP batch-verify contract
    (fabric_tpu/csp/api.py: policy evaluation tolerates invalid items).
    """
    ok = [
        _check_schnorr(s, ipk, m) for s, m in zip(sigs, msgs)
    ]
    return _pairing_mask(sigs, ok, ipk, rng)


def _pairing_mask(sigs, ok: list[bool], ipk, rng=None) -> list[bool]:
    """Combined two-pairing check over the Schnorr-surviving items with
    random weights; falls back to per-item pairings when the combined
    check fails so the result stays a per-signature mask."""
    live = [i for i, v in enumerate(ok) if v]
    if not live:
        return ok
    weights = {i: bn.rand_zr(rng) for i in live}
    acc_ap = bn.g1_msm([(sigs[i].a_prime, weights[i]) for i in live])
    acc_ab = bn.g1_msm([(sigs[i].a_bar, weights[i]) for i in live])
    if bn.pairing_check([(acc_ap, ipk.w), (bn.g1_neg(acc_ab), bn.G2_GEN)]):
        return ok
    # Rare path: at least one forged pairing — isolate per item.
    for i in live:
        ok[i] = bn.pairing_check(
            [(sigs[i].a_prime, ipk.w), (bn.g1_neg(sigs[i].a_bar), bn.G2_GEN)]
        )
    return ok


def verify_batch_device(
    sigs: list[Signature],
    ipk: IssuerPublicKey,
    msgs: list[bytes],
    rng=None,
) -> list[bool]:
    """verify_batch with the Schnorr commitment recomputation batched on
    the device (csp/tpu/bn254_batch.py — one XLA program re-derives
    every signature's T1/T2/T3 G1 MSMs); challenge re-hash and the
    RLC-collapsed pairings stay on host.  Any device-path failure falls
    back to the host implementation, so the result is always the host
    oracle's mask."""
    try:
        from fabric_tpu.csp.tpu import bn254_batch

        comms = bn254_batch.schnorr_commitments_batch(sigs, ipk)
    except Exception as exc:
        # loud fallback: otherwise a broken device path silently
        # re-measures/re-runs the host implementation
        from fabric_tpu.common.flogging import must_get_logger

        must_get_logger("idemix").warning(
            "device Schnorr path failed (%s: %s); falling back to host",
            type(exc).__name__, exc,
        )
        return verify_batch(sigs, ipk, msgs, rng=rng)
    ok: list[bool] = []
    for sig, msg, tri in zip(sigs, msgs, comms):
        if tri is None:
            ok.append(False)
            continue
        try:
            c = _challenge_bytes(
                ipk, list(tri), sig.a_prime, sig.a_bar, sig.b_prime,
                sig.nym, sig.disclosure, sig.disclosed_attrs, msg,
                sig.nonce,
            )
            ok.append(c == sig.challenge)
        except (ValueError, IndexError, KeyError, TypeError,
                OverflowError, AttributeError):
            ok.append(False)
    return _pairing_mask(sigs, ok, ipk, rng)
