"""Clean twin of fix_rpc_orphan_dirty: every call site targets a
registered method and every registered handler has a caller —
rpc-conformance stays quiet."""


class FixServer:
    def __init__(self, rpc):
        self.rpc = rpc
        self.rpc.register("fix.Ping", self._ping)

    def _ping(self, body, stream):
        return b"pong"


def probe(conn):
    return conn.call("fix.Ping", b"")
