"""fabric-tpu benchmark entry point.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

North-star metric (BASELINE.json / BASELINE.md): **committed tx/s** for
1000-tx blocks under a 3-of-5 (MAJORITY over 5 orgs) endorsement policy
— and this round the timed loop really commits: every measured run
drives `Committer.store_stream`, so MVCC validation, block-file append,
state-DB apply, and history indexing are all inside the measurement
(reference kvledger CommitLegacy, core/ledger/kvledger/kv_ledger.go:447-530,
downstream of txvalidator v20, validator.go:180-265).  The ledger is
on-disk (block files + sqlite WAL), matching the reference's
blockfile+leveldb persistence.

Baseline is the *faithful* reference-shaped host path: sequential
per-signature `ecdsa.Verify` with every sub-policy re-verifying its
signatures per tx, no verify-item interning / plan caching / creator
memo (bccsp/sw/ecdsa.go:41 + common/policies/policy.go:365-402
semantics), committing each block serially after validation the way
coordinator.StoreBlock does (gossip/privdata/coordinator.go:149).

Fairness: BOTH sides take best-of-N with the SAME N (4) over fresh
on-disk ledgers, after one warmup each — on a time-shared chip/host an
asymmetric N would score scheduling luck, not the pipeline
(round-4 verdict, weak #5).

Also reported: p99 block-validate latency (the second north-star
metric) over every per-block validate duration observed on the
measured path.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

_ROOT = os.path.dirname(os.path.abspath(__file__))


def _setup_path() -> None:
    for p in (_ROOT, os.path.join(_ROOT, "scripts"), os.path.join(_ROOT, "tests")):
        if p not in sys.path:
            sys.path.insert(0, p)


def main() -> None:
    _setup_path()
    from bench_pipeline import _build_world, _make_blocks

    from fabric_tpu.csp import SWCSP
    from fabric_tpu.ledger import LedgerProvider
    from fabric_tpu.peer.committer import Committer
    from fabric_tpu.peer.txvalidator import TxValidator
    from fabric_tpu.protos.common import common_pb2

    n_txs, n_blocks = 1000, 8
    sw = SWCSP()
    orgs, genesis = _build_world(5)
    _, bundle, blocks = _make_blocks(orgs, genesis, sw, n_txs, 3, n_blocks)

    def copies(k):
        out = []
        for j in range(k):
            b = common_pb2.Block()
            b.CopyFrom(blocks[j % n_blocks])
            out.append(b)
        return out

    tmp = tempfile.TemporaryDirectory(prefix="fabric-bench-")
    fresh_n = [0]

    def fresh_ledger():
        """A brand-new on-disk ledger (block files + sqlite WAL) holding
        only the genesis block — every timed run commits 1..n_blocks."""
        fresh_n[0] += 1
        provider = LedgerProvider(os.path.join(tmp.name, f"run{fresh_n[0]}"))
        return provider.create(genesis)

    # -- baseline: faithful host path, serial validate -> commit ----------
    warm = Committer(
        TxValidator("benchch", (wl := fresh_ledger()), bundle, sw, faithful=True),
        wl,
    )
    warm.store_block(copies(1)[0])  # EC backend init, native lib, protos
    base_best = float("inf")
    for _ in range(4):
        led = fresh_ledger()
        committer = Committer(
            TxValidator("benchch", led, bundle, sw, faithful=True), led
        )
        bs = copies(n_blocks)
        t0 = time.perf_counter()
        for b in bs:
            flags = committer.store_block(b)
            assert all(f == 0 for f in flags)
        base_best = min(base_best, time.perf_counter() - t0)
        assert led.height == 1 + n_blocks
    baseline = n_blocks * n_txs / base_best

    # -- measured: pipelined validate+commit stream, TPU batch verify -----
    try:
        from fabric_tpu.csp.tpu.provider import TPUCSP

        # flush/depth point measured on the real chip (round-5 sweep):
        # ~1-block flushes at depth 6 beat the old 2-block flushes at
        # depth 4 — the fixed dispatch cost amortizes worse than the
        # lost overlap from waiting for a second block's lanes
        csp = TPUCSP(min_device_batch=1, coalesce_lanes=4096)
        wl2 = fresh_ledger()
        Committer(
            TxValidator("benchch", wl2, bundle, csp), wl2
        ).store_block(copies(1)[0])  # compile + first transfer
    except Exception:
        csp = sw

    best = float("inf")
    commit_stages: dict = {}
    for _ in range(4):
        led = fresh_ledger()
        committer = Committer(TxValidator("benchch", led, bundle, csp), led)
        bs = copies(n_blocks)
        t0 = time.perf_counter()
        for flags in committer.store_stream(iter(bs), depth=6):
            assert all(f == 0 for f in flags)
        dt = time.perf_counter() - t0
        if dt < best:
            best = dt
            # per-stage commit breakdown of the winning run (the same
            # numbers the operations /metrics endpoint exposes as
            # ledger_commit_stage_duration histograms)
            commit_stages = dict(led.commit_stage_seconds)
        assert led.height == 1 + n_blocks
    value = n_blocks * n_txs / best

    # -- p99 block-validate latency on the measured path ------------------
    # (the reference logs per-block validate duration, validator.go:261;
    # here every serial validate() wall time over 3 fresh-ledger passes)
    lat = []
    for _ in range(3):
        led = fresh_ledger()
        v = TxValidator("benchch", led, bundle, csp)
        for b in copies(n_blocks):
            t0 = time.perf_counter()
            flags = v.validate(b)
            lat.append(time.perf_counter() - t0)
            assert all(f == 0 for f in flags)
            led.commit(b)
    lat.sort()
    p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))]

    print(
        json.dumps(
            {
                "metric": "committed_tx_per_s_1000tx_3of5_stream",
                "value": round(value, 2),
                "unit": "tx/s",
                "vs_baseline": round(value / baseline, 3),
                "baseline_tx_per_s": round(baseline, 2),
                "p99_block_validate_ms": round(p99 * 1e3, 2),
                "commit_stage_ms": {
                    k: round(v * 1e3, 2)
                    for k, v in sorted(commit_stages.items())
                },
            }
        )
    )
    sys.stdout.flush()
    # quiesce the device provider AFTER the one JSON line is out (a
    # wedged chip must not discard completed measurements) but BEFORE
    # interpreter exit: joining the flush waiters is what lets teardown
    # run cleanly — a tpu-flush-waiter still inside an XLA kernel at
    # exit is killed mid-unwind and glibc aborts with "FATAL: exception
    # not rethrown" (the old os._exit(0) workaround this close
    # replaces).  close() is the indefinite join: exiting under a live
    # waiter would reproduce the abort, while a genuinely wedged chip
    # is the harness timeout's problem.
    close = getattr(csp, "close", None)
    if close is not None:
        close()
    tmp.cleanup()


if __name__ == "__main__":
    main()
    sys.stdout.flush()
