"""ACL catalog fail-closed regression (ADVICE r5): an uncataloged
system-chaincode function must be DENIED, not silently exempted from the
ACL check, and the lscc/_lifecycle install & query-installed family (and
the GetChaincodesResult dispatch alias) are cataloged under explicit
policies.  Pure-unit (no crypto stack): the endorser-level enforcement
rides resource_for_chaincode raising ACLError."""

import pytest

from fabric_tpu.peer import aclmgmt
from fabric_tpu.peer.aclmgmt import (
    ACLError,
    DEFAULT_POLICIES,
    SCC_FUNCTION_RESOURCES,
    resource_for_chaincode,
)

ADMINS = "/Channel/Application/Admins"


def test_uncataloged_scc_function_denied():
    for cc, fn in (
        ("qscc", "TotallyMadeUp"),
        ("lscc", "getchaincodedata-typo"),
        ("_lifecycle", "NotAFunction"),
        ("cscc", ""),
    ):
        with pytest.raises(ACLError):
            resource_for_chaincode(cc, fn)


def test_application_chaincode_still_propose():
    assert resource_for_chaincode("mycc", "anything") == aclmgmt.PEER_PROPOSE


def test_install_family_cataloged_under_admins():
    for cc, fn, resource in (
        ("lscc", "install", aclmgmt.LSCC_INSTALL),
        ("lscc", "getinstalledchaincodes", aclmgmt.LSCC_GET_INSTALLED_CC),
        ("_lifecycle", "InstallChaincode", aclmgmt.LIFECYCLE_INSTALL),
        ("_lifecycle", "QueryInstalledChaincodes",
         aclmgmt.LIFECYCLE_QUERY_INSTALLED),
        ("_lifecycle", "GetInstalledChaincodePackage",
         aclmgmt.LIFECYCLE_GET_PACKAGE),
    ):
        assert resource_for_chaincode(cc, fn) == resource
        assert DEFAULT_POLICIES[resource] == ADMINS


def test_getchaincodesresult_alias_matches_getchaincodes():
    assert (
        resource_for_chaincode("lscc", "GetChaincodesResult")
        == resource_for_chaincode("lscc", "getchaincodes")
        == aclmgmt.LSCC_GET_CHAINCODES
    )


def test_every_cataloged_resource_has_a_default_policy():
    for resource in SCC_FUNCTION_RESOURCES.values():
        assert resource in DEFAULT_POLICIES, resource
