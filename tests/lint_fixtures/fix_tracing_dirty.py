"""SEEDED VIOLATION (lock-discipline): a homegrown "trace dump" helper
doing real blocking I/O is NOT the reviewed tracing seam — reaching it
while holding the commit lock must fire.  Paired with
fix_tracing_clean.py, this pins that the chaos-seam exemption is scoped
to fabric_tpu/common/tracing.py itself, not to anything trace-shaped."""


def dump_spans(fh, doc: str) -> None:
    fh.write(doc)
    fh.flush()  # blocking: summarized, and NOT seam-exempt


class Ledger:
    def __init__(self, lock, fh):
        self.commit_lock = lock
        self._fh = fh

    def commit(self):
        with self.commit_lock:
            dump_spans(self._fh, "{}")  # <- lock-discipline fires HERE
