"""fabriclint dataflow — the whole-program half of the invariant checker.

PR 3's fabriclint sees one function at a time, so a digest computed in a
helper, wall-clock smuggled through two assignments into a marshaled
header, or an fsync three calls below ``commit_lock`` all slipped past
the gate.  This module closes that class: it parses every module in the
lint target set ONCE, resolves module-level imports and aliases
(``import hashlib as h``, ``from time import time``, relative imports),
builds a call graph over names it can resolve statically (module-level
functions, same-module helpers, ``self.`` methods of the enclosing
class), and computes per-function summaries to a fixpoint:

``uses_hashlib`` / ``uses_hashlib_transitive``
    touches ``hashlib`` directly / reaches it through helpers whose own
    modules are outside the CSP seam (propagation STOPS at seam modules:
    calling ``common.hashing.sha256`` is the fix, not a violation).

``returns_digest``
    returns a value produced by a hash call (hashlib, the seam's
    sha256/sha256_many, a CSP ``hash``/``hash_batch``) — directly or via
    a digest-returning callee.

``blocking`` / ``blocking_transitive``
    performs blocking I/O (fsync/flush/execute/sleep...) directly / via
    any resolvable call chain.  lint.py uses this to extend the
    under-``commit_lock`` rule across function and module boundaries.

``spawns_thread`` / ``acquires_locks``
    creates ``threading.Thread``s / lexically ``with``-acquires known
    lock roles — thread-lifecycle and lock-order context for reviewers
    and the thread-hygiene rule.

``returns_wallclock`` / ``param_to_return`` / ``param_to_sink``
    the taint summaries: the function returns a wall-clock-derived
    value; parameter *i* flows to the return value; parameter *i* flows
    into a consensus-bytes sink (protoutil call, protobuf constructor,
    ``SerializeToString``).

On top of the summaries run the interprocedural emissions:

taint
    ``time.time()`` / ``datetime.now()`` / module-level ``random.*``
    values tracked through assignments, attribute fills
    (``hdr.timestamp = ts``), f-strings, arithmetic, and resolvable
    calls, flagged where they ENTER a sink — protoutil marshaling or
    protobuf (block-header) construction — whichever module that happens
    in.  Tainted ``self`` attributes propagate across methods of the
    same class (``self._inc = int(time.time()*1000)`` in ``__init__``
    taints ``self._inc`` in every other method).

csp-seam (alias half)
    a local binding to ``hashlib`` (``h = hashlib``;
    ``digest = h.sha256``) used outside the seam — the spelling the
    intraprocedural attribute check cannot see.  The helper-call half
    (callers of hashlib-using helpers) is emitted by lint.py's checker
    using ``call_resolutions`` + the summaries here.

racecheck (v3)
    whole-program lockset inference + shared-state race detection.  A
    CLASS REGISTRY records, per class, which ``self.<attr>`` members
    are locks (``named_lock/named_rlock/named_condition`` roles, or a
    ``<Class>.<attr>`` pseudo-role for plain ``threading.Lock()``
    members) and which carry a statically known class type (annotated
    params/fields, direct constructor assignments) — the latter powers
    TYPE-INFORMED CALL RESOLUTION, so ``ledger.commit(...)`` on a
    ``ledger: KVLedger`` parameter lands in the call graph instead of
    falling off it.  A LOCKSET PASS then records, for every
    ``self._x`` (and declared module-global) read or write, the set of
    lock roles lexically held at that point, plus the lockset held at
    every resolvable call site; an interprocedural meet (set
    intersection over all incoming call paths) extends those locksets
    across function boundaries.  Fields acquire a GUARDED-BY role from
    the reviewed declaration table (``devtools/guards.py``) or, for
    undeclared mutable fields, by majority inference across their
    access sites.  Any access on a path from a THREAD ENTRY POINT
    (``lockwatch.spawn_thread``/``spawn_timer`` targets,
    ``threading.Thread``/``Timer`` ctors, ``executor.submit``, RPC/
    gossip ``.register``/``.subscribe`` handlers) whose lockset misses
    the field's guard is emitted as a racecheck flow.  ``__init__``
    bodies are excluded (the object is unpublished), a with-context
    that looks like a lock but cannot be resolved contributes an
    UNKNOWN token that suppresses rather than fabricates findings, and
    fields never written outside ``__init__`` are immune — three
    precision rules that keep the rule deployable at error severity.

The engine is deliberately static and approximate: only statically
resolvable names participate in the call graph, attribute calls on
foreign objects fall back to the per-name heuristics, and taint is
flow-insensitively accumulated (two body iterations per round).  The
approximations are all CONSERVATIVE for the rules built on top, and
every false positive costs exactly one reviewed pragma — the currency
this linter already trades in.
"""

from __future__ import annotations

import ast
import dataclasses

# modules allowed to touch hashlib directly — the canonical definition
# (lint.py imports it from here so the two passes can never disagree)
CSP_SEAM_ALLOWED = (
    "fabric_tpu/csp/",
    "fabric_tpu/common/hashing.py",
    "fabric_tpu/common/crypto.py",
)

BLOCKING_CALLS = frozenset(
    {"fsync", "sync_files", "sleep", "flush", "execute", "executemany"}
)

# taint sinks: consensus bytes are born in these places
_SINK_MODULE_PREFIXES = ("fabric_tpu.protoutil", "fabric_tpu.protos.")
_SINK_ATTRS = frozenset({"SerializeToString", "SerializeToOstream"})

# hash producers for the returns-digest summary
_SEAM_HASH_FNS = (
    "fabric_tpu.common.hashing.sha256",
    "fabric_tpu.common.hashing.sha256_many",
    "fabric_tpu.common.crypto.sha256",
    "fabric_tpu.common.crypto.sha256_many",
)
_HASH_ATTRS = frozenset({"hash", "hash_batch", "digest", "hexdigest"})

_WALL = "wall"
_MAX_ROUNDS = 12

# -- racecheck vocabulary ----------------------------------------------------

# lock constructors recognized on `self.<attr> = ...` / module globals;
# named_* carry an explicit lockwatch role, plain threading primitives
# get a `<owner>.<attr>` pseudo-role so their guarded fields still
# participate in lockset inference
_NAMED_LOCK_FNS = frozenset({
    "fabric_tpu.devtools.lockwatch.named_lock",
    "fabric_tpu.devtools.lockwatch.named_rlock",
    "fabric_tpu.devtools.lockwatch.named_condition",
})
_PLAIN_LOCK_FNS = frozenset({
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
})

_SPAWN_THREAD_FNS = frozenset({
    "fabric_tpu.devtools.lockwatch.spawn_thread",
    "threading.Thread",
})
_SPAWN_TIMER_FNS = frozenset({
    "fabric_tpu.devtools.lockwatch.spawn_timer",
    "threading.Timer",
})
# attribute calls whose function-valued arguments run on foreign
# threads: executor submissions and RPC/gossip handler registration
_SUBMIT_ATTRS = frozenset({"submit"})
_HANDLER_REG_ATTRS = frozenset({"register", "subscribe"})

# a with-context that names a lock we cannot resolve to a role: it MAY
# be the guard, so accesses under it are never flagged and never feed
# majority inference
_UNKNOWN_LOCK = "?"

# gossip payload digests are consensus-adjacent bytes: peers compare /
# request private data by these digests, so a wall-clock-derived value
# entering one forks the gossip view exactly like a forked block header.
# Sink = the seam hash functions when called from gossip modules.
_GOSSIP_SINK_SCOPE = "fabric_tpu/gossip/"


# the chaos/observability seams: their blocking calls (faultline.
# write's torn-path flush, clockskew/faultline injected sleeps,
# tracing's flight-recorder dump/export I/O) only execute under an
# armed plan / virtual clock / armed tracer — with nothing armed every
# seam call is a no-op, so their blocking-io summaries must not
# propagate into callers (mirror of the PR 6 decision that faultline.*
# is transparent to exception-discipline)
_CHAOS_SEAM = (
    "fabric_tpu/devtools/faultline.py",
    "fabric_tpu/devtools/clockskew.py",
    "fabric_tpu/common/tracing.py",
)


def _in_seam(rel: str) -> bool:
    return any(rel.startswith(p) for p in CSP_SEAM_ALLOWED)


def _module_dotted(rel: str) -> str:
    """Repo-relative path -> dotted module name."""
    if rel.endswith("/__init__.py"):
        rel = rel[: -len("/__init__.py")]
    elif rel.endswith(".py"):
        rel = rel[:-3]
    return rel.replace("/", ".")


def _iter_nested_defs(stmts):
    """Function definitions nested one level down inside a statement
    list (descending through control flow but not into the found defs
    themselves — recursion registers deeper levels — nor into nested
    classes, which are out of model)."""
    for s in stmts:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield s
        elif isinstance(s, ast.ClassDef):
            continue
        else:
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(s, attr, None)
                if sub:
                    yield from _iter_nested_defs(sub)
            for h in getattr(s, "handlers", ()):
                yield from _iter_nested_defs(h.body)
            for c in getattr(s, "cases", ()):  # match statements
                yield from _iter_nested_defs(c.body)


def _dotted(expr) -> str | None:
    """``a.b.c`` as a string; None for anything fancier."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if not isinstance(expr, ast.Name):
        return None
    parts.append(expr.id)
    return ".".join(reversed(parts))


@dataclasses.dataclass
class FunctionInfo:
    rel: str
    qname: str  # dotted: module[.Class].name
    name: str
    cls: str | None
    lineno: int
    params: list[str]
    node: object  # ast.FunctionDef | ast.AsyncFunctionDef
    # direct facts
    uses_hashlib: bool = False
    blocking: bool = False
    spawns_thread: bool = False
    acquires_locks: set = dataclasses.field(default_factory=set)
    calls: list = dataclasses.field(default_factory=list)  # resolved qnames
    # fixpoint facts
    uses_hashlib_transitive: bool = False
    blocking_transitive: bool = False
    returns_digest: bool = False
    returns_wallclock: bool = False
    param_to_return: set = dataclasses.field(default_factory=set)
    param_to_sink: set = dataclasses.field(default_factory=set)
    # racecheck facts: (field qname, "read"|"write", line, frozenset of
    # lock roles lexically held) and (callee qname, frozenset held)
    accesses: list = dataclasses.field(default_factory=list)
    call_locks: list = dataclasses.field(default_factory=list)

    def summary(self) -> dict:
        """JSON-shaped summary (CLI ``--summaries``, tests)."""
        return {
            "function": self.qname,
            "file": self.rel,
            "line": self.lineno,
            "returns_digest": self.returns_digest,
            "returns_wallclock": self.returns_wallclock,
            "uses_hashlib": self.uses_hashlib_transitive,
            "blocking_io": self.blocking_transitive,
            "spawns_thread": self.spawns_thread,
            "acquires_locks": sorted(self.acquires_locks),
            "param_to_sink": sorted(self.param_to_sink),
            "accesses": len(self.accesses),
        }


@dataclasses.dataclass
class ClassInfo:
    """Per-class registry entry for racecheck + typed call resolution."""

    rel: str
    qname: str
    name: str
    # attr -> lock role (lockwatch role string, or qname pseudo-role)
    lock_roles: dict = dataclasses.field(default_factory=dict)
    # attr -> class qname (annotated params/fields, ctor assignments)
    field_types: dict = dataclasses.field(default_factory=dict)
    # every attr assigned through `self.` anywhere in the class
    fields: set = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class ModuleInfo:
    rel: str
    dotted: str
    tree: ast.Module
    imports: dict = dataclasses.field(default_factory=dict)  # name -> dotted
    functions: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class TaintFlow:
    """One wall-clock value entering a consensus-bytes sink."""

    rel: str
    line: int
    message: str


class Project:
    """Whole-program model over the lint target set.

    ``sanctioned_sources`` maps rel -> line numbers whose wall-clock
    source calls are covered by a reviewed ``allow[determinism]`` or
    ``allow[taint]`` pragma: a REVIEWED source does not propagate —
    otherwise one sanctioned client-side timestamp would demand a
    pragma at every downstream marshal site, and the suppression
    surface would grow instead of shrink."""

    def __init__(self, trees: dict[str, ast.Module],
                 sanctioned_sources: dict[str, set] | None = None,
                 declared_guards: dict[str, str] | None = None):
        if declared_guards is None:
            from fabric_tpu.devtools.guards import DECLARED_GUARDS

            declared_guards = DECLARED_GUARDS
        self.declared_guards = dict(declared_guards)
        self.sanctioned_sources = sanctioned_sources or {}
        # (rel, line) of sanctioned sources the engine actually hit —
        # lint.py counts their pragmas as used (the pragma's job was to
        # stop propagation, not to suppress a same-line violation)
        self.sanctioned_used: set[tuple] = set()
        self.modules: dict[str, ModuleInfo] = {}
        self.symbols: dict[str, FunctionInfo] = {}
        # (rel, lineno, col_offset) of a Call node -> resolved callee qname
        self.call_resolutions: dict[tuple, str] = {}
        # csp-seam alias violations found during the facts pass
        self.alias_violations: list[TaintFlow] = []
        self.taint_flows: list[TaintFlow] = []
        # racecheck emissions + the inferred guarded-by map behind them
        self.race_flows: list[TaintFlow] = []
        self.guard_map: dict[str, dict] = {}
        # class registry (racecheck + typed call resolution)
        self.classes: dict[str, ClassInfo] = {}
        self.module_lock_roles: dict[str, str] = {}  # dotted name -> role
        self._attr_role_unique: dict[str, str | None] = {}
        # fn qname -> how it becomes a thread entry (for messages)
        self.thread_entries: dict[str, str] = {}
        # ClassDef qname -> names of self attributes holding wall-clock
        self._class_taint: dict[str, set] = {}
        for rel, tree in sorted(trees.items()):
            self._load_module(rel, tree)
        self._collect_classes()
        self._collect_facts()
        self._fixpoint_booleans()
        self._fixpoint_taint()
        self._lockset_pass_all()
        self._racecheck()

    # -- module loading ----------------------------------------------------

    def _load_module(self, rel: str, tree: ast.Module) -> None:
        mod = ModuleInfo(rel=rel, dotted=_module_dotted(rel), tree=tree)
        pkg = mod.dotted.rsplit(".", 1)[0] if "." in mod.dotted else ""
        if rel.endswith("/__init__.py"):
            pkg = mod.dotted
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mod.imports[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
                    if a.asname:
                        mod.imports[a.asname] = a.name
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    up = pkg.split(".") if pkg else []
                    up = up[: len(up) - (node.level - 1)]
                    base = ".".join(up + ([node.module] if node.module else []))
                for a in node.names:
                    if a.name == "*":
                        continue
                    mod.imports[a.asname or a.name] = (
                        f"{base}.{a.name}" if base else a.name
                    )
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_function(mod, stmt, cls=None)
            elif isinstance(stmt, ast.ClassDef):
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self._add_function(mod, sub, cls=stmt.name)
        self.modules[rel] = mod

    def _add_function(self, mod: ModuleInfo, node, cls: str | None,
                      parent: str | None = None) -> None:
        if parent is not None:
            qname = f"{parent}.<locals>.{node.name}"
        else:
            qname = (
                f"{mod.dotted}.{cls}.{node.name}" if cls
                else f"{mod.dotted}.{node.name}"
            )
        a = node.args
        params = [p.arg for p in a.posonlyargs + a.args]
        fn = FunctionInfo(
            rel=mod.rel, qname=qname, name=node.name, cls=cls,
            lineno=node.lineno, params=params, node=node,
        )
        mod.functions.append(fn)
        self.symbols[qname] = fn
        # locally-defined functions get their own symbols under a
        # `<qname>.<locals>.` scope: closures passed to spawn_thread /
        # Thread (the committer's commit_loop, rpc's stream pull) are
        # real thread entries racecheck must see.  They keep the
        # enclosing `cls` so closed-over `self.x` accesses resolve into
        # the class registry.
        for sub in _iter_nested_defs(node.body):
            self._add_function(mod, sub, cls=cls, parent=qname)

    # -- name resolution ---------------------------------------------------

    def _resolve_expr(self, mod: ModuleInfo, expr, cls: str | None,
                      local: dict, types: dict | None = None) -> str | None:
        """Resolve a Name/Attribute chain to a dotted target through
        local bindings, module imports, and (when `types` maps names to
        class qnames) annotated-parameter/field types.  ``self.x``
        resolves into the enclosing class; ``self.f.m`` and ``p.m``
        resolve through the class registry when ``f``/``p`` have a
        statically known class.  Returns e.g. "hashlib.sha256",
        "time.time", "fabric_tpu.ledger.kvledger.KVLedger.commit"."""
        dotted = _dotted(expr)
        if dotted is None:
            return None
        head, _, rest = dotted.partition(".")
        if head == "self" and cls is not None:
            if not rest:
                return None
            first, _, tail = rest.partition(".")
            if tail:
                # typed self-field chain: self._ledger.commit resolves
                # through the field's declared/constructed class
                ci = self.classes.get(f"{mod.dotted}.{cls}")
                ft = ci.field_types.get(first) if ci else None
                if ft is not None:
                    return f"{ft}.{tail}"
            return f"{mod.dotted}.{cls}.{rest}"
        if types and rest and head in types:
            return f"{types[head]}.{rest}"
        target = local.get(head) or mod.imports.get(head)
        if target is None:
            # same-module symbol?
            cand = f"{mod.dotted}.{dotted}"
            if cand in self.symbols:
                return cand
            return None
        return f"{target}.{rest}" if rest else target

    # -- class registry (racecheck + typed resolution) ---------------------

    def _annotation_class(self, mod: ModuleInfo, ann) -> str | None:
        """The class qname an annotation statically names, or None.
        Handles Name/Attribute, string annotations, ``X | None`` unions
        and ``Optional[X]`` — anything fancier is out of model."""
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
        if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
            return (self._annotation_class(mod, ann.left)
                    or self._annotation_class(mod, ann.right))
        if isinstance(ann, ast.Subscript):
            base = _dotted(ann.value)
            if base is not None and base.rsplit(".", 1)[-1] == "Optional":
                return self._annotation_class(mod, ann.slice)
            return None
        if not isinstance(ann, (ast.Name, ast.Attribute)):
            return None
        target = self._resolve_expr(mod, ann, None, {})
        if target in self.classes:
            return target
        return None

    @staticmethod
    def _role_from_ctor(target: str | None, call: ast.Call,
                        pseudo: str) -> str | None:
        """Lock role for a `<member> = <lock ctor>(...)` assignment:
        the named_* role string when constant, else the member's own
        qname as a pseudo-role (plain threading primitives included)."""
        if target in _NAMED_LOCK_FNS:
            if (
                call.args
                and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, str)
            ):
                return call.args[0].value
            return pseudo
        if target in _PLAIN_LOCK_FNS:
            return pseudo
        return None

    def _collect_classes(self) -> None:
        # phase 1: every class must exist before any annotation can
        # resolve to it (cross-module field types)
        for mod in self.modules.values():
            for stmt in mod.tree.body:
                if isinstance(stmt, ast.ClassDef):
                    q = f"{mod.dotted}.{stmt.name}"
                    self.classes[q] = ClassInfo(
                        rel=mod.rel, qname=q, name=stmt.name
                    )
                elif (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Call)
                ):
                    # module-level locks guard module-level state
                    name = stmt.targets[0].id
                    target = self._resolve_expr(mod, stmt.value.func, None, {})
                    role = self._role_from_ctor(
                        target, stmt.value, f"{mod.dotted}.{name}"
                    )
                    if role is not None:
                        self.module_lock_roles[f"{mod.dotted}.{name}"] = role
        # phase 2: member scan (locks, field types, assigned attrs)
        for mod in self.modules.values():
            for stmt in mod.tree.body:
                if not isinstance(stmt, ast.ClassDef):
                    continue
                ci = self.classes[f"{mod.dotted}.{stmt.name}"]
                for fnnode in stmt.body:
                    if not isinstance(
                        fnnode, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        continue
                    a = fnnode.args
                    ann_params = {
                        p.arg: p.annotation
                        for p in a.posonlyargs + a.args + a.kwonlyargs
                        if p.annotation is not None
                    }
                    for node in ast.walk(fnnode):
                        if (
                            isinstance(node, ast.Assign)
                            and len(node.targets) == 1
                            and isinstance(node.targets[0], ast.Attribute)
                            and isinstance(node.targets[0].value, ast.Name)
                            and node.targets[0].value.id == "self"
                        ):
                            attr = node.targets[0].attr
                            ci.fields.add(attr)
                            v = node.value
                            if isinstance(v, ast.Call):
                                target = self._resolve_expr(
                                    mod, v.func, stmt.name, {}
                                )
                                role = self._role_from_ctor(
                                    target, v, f"{ci.qname}.{attr}"
                                )
                                if role is not None:
                                    ci.lock_roles[attr] = role
                                elif target in self.classes:
                                    ci.field_types.setdefault(attr, target)
                            elif (
                                isinstance(v, ast.Name)
                                and v.id in ann_params
                            ):
                                tq = self._annotation_class(
                                    mod, ann_params[v.id]
                                )
                                if tq is not None:
                                    ci.field_types.setdefault(attr, tq)
                        elif (
                            isinstance(node, (ast.AnnAssign, ast.AugAssign))
                            and isinstance(node.target, ast.Attribute)
                            and isinstance(node.target.value, ast.Name)
                            and node.target.value.id == "self"
                        ):
                            ci.fields.add(node.target.attr)
                            if isinstance(node, ast.AnnAssign):
                                tq = self._annotation_class(
                                    mod, node.annotation
                                )
                                if tq is not None:
                                    ci.field_types[node.target.attr] = tq
        # attr name -> role when ONE role owns that spelling across the
        # whole program: lets `with self._ledger.commit_lock:` resolve
        # even where the field's type is unannotated
        unique: dict[str, str | None] = {}
        for ci in self.classes.values():
            for attr, role in ci.lock_roles.items():
                if attr in unique and unique[attr] != role:
                    unique[attr] = None
                else:
                    unique[attr] = role
        self._attr_role_unique = unique

    # -- facts pass --------------------------------------------------------

    def _collect_facts(self) -> None:
        for mod in self.modules.values():
            for fn in mod.functions:
                self._facts_for(mod, fn)

    def _facts_for(self, mod: ModuleInfo, fn: FunctionInfo) -> None:
        local: dict[str, str] = {}
        seam = _in_seam(mod.rel)
        # annotated params with statically known classes: the type env
        # behind type-informed call resolution
        a = fn.node.args
        types: dict[str, str] = {}
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            tq = self._annotation_class(mod, p.annotation)
            if tq is not None:
                types[p.arg] = tq
        fn._types = types
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                # `types` rides along so a local bound from an annotated
                # param's field (`lk = ledger.commit_lock`) resolves to
                # the field's qname — the lockset pass then maps the
                # bare `with lk:` to the field's lock role
                bound = self._resolve_expr(
                    mod, node.value, fn.cls, local, types
                )
                if bound is not None and not isinstance(node.value, ast.Call):
                    local[node.targets[0].id] = bound
                    if not seam and (
                        bound == "hashlib" or bound.startswith("hashlib.")
                    ):
                        self.alias_violations.append(TaintFlow(
                            rel=mod.rel, line=node.lineno,
                            message=f"local alias "
                                    f"{node.targets[0].id!r} binds "
                                    f"{bound} outside the CSP seam — "
                                    "aliasing does not launder a direct "
                                    "hashlib dependency (route through "
                                    "common.hashing or the CSP)",
                        ))
            elif isinstance(node, ast.Call):
                target = self._resolve_expr(
                    mod, node.func, fn.cls, local, types
                )
                if target is not None:
                    if target in self.symbols:
                        fn.calls.append(target)
                        self.call_resolutions[
                            (mod.rel, node.lineno, node.col_offset)
                        ] = target
                    if target == "hashlib" or target.startswith("hashlib."):
                        fn.uses_hashlib = True
                    if target in (
                        "threading.Thread",
                        "threading.Timer",
                        "fabric_tpu.devtools.lockwatch.spawn_thread",
                        "fabric_tpu.devtools.lockwatch.spawn_timer",
                    ):
                        fn.spawns_thread = True
                f = node.func
                if isinstance(f, ast.Attribute):
                    if f.attr in BLOCKING_CALLS:
                        fn.blocking = True
                    if (
                        isinstance(f.value, ast.Name)
                        and local.get(f.value.id, "").startswith("hashlib")
                    ):
                        fn.uses_hashlib = True
            elif isinstance(node, ast.With):
                for item in node.items:
                    name = None
                    ctx = item.context_expr
                    if isinstance(ctx, ast.Attribute):
                        name = ctx.attr
                    elif isinstance(ctx, ast.Name):
                        name = ctx.id
                    if name is not None and (
                        "lock" in name.lower() or name in ("_idle",)
                    ):
                        fn.acquires_locks.add(name)
        fn.uses_hashlib_transitive = fn.uses_hashlib and not seam
        fn.blocking_transitive = fn.blocking and fn.rel not in _CHAOS_SEAM
        fn.returns_digest = self._returns_digest_direct(mod, fn, local)
        fn._local_bindings = local  # reused by the taint pass
        # names stored more than once anywhere in this function: a lock
        # ALIAS among them is ambiguous — the binding map is flow-
        # insensitive (last write wins), so crediting it would attach
        # the WRONG lock's role to earlier with-blocks.  _role_of_ctx
        # degrades rebound aliases to the UNKNOWN lockset instead.
        store_counts: dict[str, int] = {}
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Store
            ):
                store_counts[node.id] = store_counts.get(node.id, 0) + 1
        fn._rebound = {k for k, c in store_counts.items() if c > 1}
        # callee qnames appearing inside Return expressions, computed
        # once — the returns-digest fixpoint is a set lookup, not a
        # re-walk of the caller's AST per round
        ret_calls: set = set()
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Return) and node.value is not None:
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Call):
                        q = self.call_resolutions.get(
                            (mod.rel, sub.lineno, sub.col_offset)
                        )
                        if q is not None:
                            ret_calls.add(q)
        fn._return_callees = ret_calls

    def _returns_digest_direct(self, mod: ModuleInfo, fn: FunctionInfo,
                               local: dict) -> bool:
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            for sub in ast.walk(node.value):
                if not isinstance(sub, ast.Call):
                    continue
                target = self._resolve_expr(mod, sub.func, fn.cls, local)
                if target is not None and (
                    target.startswith("hashlib.")
                    or target in _SEAM_HASH_FNS
                ):
                    return True
                if isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr in _HASH_ATTRS:
                    return True
        return False

    # -- boolean fixpoints -------------------------------------------------

    def _fixpoint_booleans(self) -> None:
        changed = True
        rounds = 0
        while changed and rounds < _MAX_ROUNDS:
            changed = False
            rounds += 1
            for fn in self.symbols.values():
                for callee_q in fn.calls:
                    callee = self.symbols.get(callee_q)
                    if callee is None:
                        continue
                    if callee.blocking_transitive and not fn.blocking_transitive:
                        fn.blocking_transitive = True
                        changed = True
                    # hashlib reach propagates only through NON-seam
                    # callees: calling the seam is the sanctioned route
                    if (
                        callee.uses_hashlib_transitive
                        and not _in_seam(callee.rel)
                        and not _in_seam(fn.rel)
                        and not fn.uses_hashlib_transitive
                    ):
                        fn.uses_hashlib_transitive = True
                        changed = True
                    if (
                        callee.returns_digest
                        and not fn.returns_digest
                        and callee_q in fn._return_callees
                    ):
                        fn.returns_digest = True
                        changed = True

    # -- taint -------------------------------------------------------------

    def _fixpoint_taint(self) -> None:
        for _ in range(_MAX_ROUNDS):
            changed = False
            for mod in self.modules.values():
                for fn in mod.functions:
                    if self._taint_pass(mod, fn, emit=False):
                        changed = True
            if not changed:
                break
        seen = set()
        for mod in self.modules.values():
            for fn in mod.functions:
                self._taint_pass(mod, fn, emit=True, seen=seen)

    def _is_wall_source(self, target: str | None) -> bool:
        if target is None:
            return False
        if target == "time.time":
            return True
        if target.startswith("datetime.") and target.rsplit(".", 1)[-1] in (
            "now", "utcnow", "today"
        ):
            return True
        if target.startswith("random.") and target.rsplit(".", 1)[-1] not in (
            "Random", "SystemRandom"
        ):
            return True
        return False

    def _sink_for(self, mod: ModuleInfo, node: ast.Call, cls, local):
        """(kind, detail) when this call consumes its arguments into
        consensus bytes; None otherwise."""
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _SINK_ATTRS:
            return ("serialize", f.attr)
        target = self._resolve_expr(mod, f, cls, local)
        if target is not None and any(
            target.startswith(p) for p in _SINK_MODULE_PREFIXES
        ):
            tail = target.rsplit(".", 1)[-1]
            kind = "proto-ctor" if tail[:1].isupper() else "protoutil"
            return (kind, target)
        # gossip payload digests: peers dedupe/pull/verify by these
        # bytes, so a wall-clock-derived input forks the gossip view
        if (
            mod.rel.startswith(_GOSSIP_SINK_SCOPE)
            and target is not None
            and (target in _SEAM_HASH_FNS
                 or target.startswith("hashlib."))
        ):
            return ("gossip-digest", target)
        return None

    def _taint_pass(self, mod: ModuleInfo, fn: FunctionInfo,
                    emit: bool, seen: set | None = None) -> bool:
        env: dict[str, frozenset] = {
            p: frozenset({("param", i)}) for i, p in enumerate(fn.params)
        }
        if fn.cls is not None and fn.params and fn.params[0] == "self":
            env["self"] = frozenset()
        cls_q = f"{mod.dotted}.{fn.cls}" if fn.cls else None
        local = getattr(fn, "_local_bindings", {})
        changed = [False]

        def note_param_summary(labels, add_to: set) -> None:
            for lb in labels:
                if isinstance(lb, tuple) and lb[0] == "param":
                    if lb[1] not in add_to:
                        add_to.add(lb[1])
                        changed[0] = True

        def ev(node) -> frozenset:
            if isinstance(node, ast.Name):
                return env.get(node.id, frozenset())
            if isinstance(node, ast.Constant):
                return frozenset()
            if isinstance(node, ast.Call):
                return ev_call(node)
            if isinstance(node, ast.Attribute):
                base = ev(node.value)
                dotted = _dotted(node)
                if dotted is not None and dotted in env:
                    base = base | env[dotted]
                if (
                    cls_q is not None
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and node.attr in self._class_taint.get(cls_q, ())
                ):
                    base = base | frozenset({_WALL})
                return base
            if isinstance(node, ast.JoinedStr):
                out = frozenset()
                for v in node.values:
                    out |= ev(v)
                return out
            if isinstance(node, ast.FormattedValue):
                return ev(node.value)
            out = frozenset()
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    out |= ev(child)
            return out

        def arg_labels(node: ast.Call, callee: FunctionInfo | None):
            """position -> labels, including keywords mapped through the
            callee's parameter names (methods: skip the self slot)."""
            out: dict[int, frozenset] = {}
            shift = 1 if callee is not None and callee.params[:1] == ["self"] \
                else 0
            for i, a in enumerate(node.args):
                out[i + shift] = ev(a)
            for kw in node.keywords:
                labels = ev(kw.value)
                if callee is not None and kw.arg in (callee.params or ()):
                    out[callee.params.index(kw.arg)] = labels
                else:
                    out.setdefault(-1, frozenset())
                    out[-1] |= labels
            return out

        def ev_call(node: ast.Call) -> frozenset:
            callee_q = self.call_resolutions.get(
                (mod.rel, node.lineno, node.col_offset)
            )
            callee = self.symbols.get(callee_q) if callee_q else None
            target = self._resolve_expr(mod, node.func, fn.cls, local)
            if self._is_wall_source(target):
                if node.lineno in self.sanctioned_sources.get(mod.rel, ()):
                    self.sanctioned_used.add((mod.rel, node.lineno))
                else:
                    return frozenset({_WALL})
            labels_by_pos = arg_labels(node, callee)
            sink = self._sink_for(mod, node, fn.cls, local)
            flowing = frozenset()
            for labels in labels_by_pos.values():
                flowing |= labels
            if isinstance(node.func, ast.Attribute) and sink:
                flowing |= ev(node.func.value)
                # a proto object filled field-by-field: any tainted
                # `obj.field` entry counts against `obj.Serialize...()`
                base_d = _dotted(node.func.value)
                if base_d is not None:
                    for k, v in env.items():
                        if k.startswith(base_d + "."):
                            flowing |= v
            if sink is not None:
                if _WALL in flowing:
                    if emit:
                        key = ("taint", mod.rel, node.lineno)
                        if seen is not None and key not in seen:
                            seen.add(key)
                            self.taint_flows.append(TaintFlow(
                                rel=mod.rel, line=node.lineno,
                                message=(
                                    "wall-clock-derived value flows into "
                                    f"consensus bytes ({sink[0]}: "
                                    f"{sink[1]}) — peers recomputing "
                                    "these bytes will disagree; thread "
                                    "an explicit timestamp argument "
                                    "instead"
                                ),
                            ))
                note_param_summary(flowing, fn.param_to_sink)
            if callee is not None:
                # arguments reaching the callee's sink-flowing params
                for pos, labels in labels_by_pos.items():
                    if pos in callee.param_to_sink:
                        if _WALL in labels and emit:
                            key = ("taint", mod.rel, node.lineno)
                            if seen is not None and key not in seen:
                                seen.add(key)
                                self.taint_flows.append(TaintFlow(
                                    rel=mod.rel, line=node.lineno,
                                    message=(
                                        "wall-clock-derived argument "
                                        f"reaches a consensus-bytes sink "
                                        f"inside {callee.qname} (param "
                                        f"{pos}) — peers recomputing "
                                        "these bytes will disagree"
                                    ),
                                ))
                        note_param_summary(labels, fn.param_to_sink)
                out = frozenset()
                if callee.returns_wallclock:
                    out |= frozenset({_WALL})
                for pos in callee.param_to_return:
                    out |= labels_by_pos.get(pos, frozenset())
                return out
            # unresolved call: conservatively propagate every input
            out = flowing
            if isinstance(node.func, ast.Attribute):
                out |= ev(node.func.value)
            return out

        def assign_to(target, labels: frozenset) -> None:
            if isinstance(target, ast.Name):
                prev = env.get(target.id, frozenset())
                if labels - prev:
                    env[target.id] = prev | labels
            elif isinstance(target, ast.Attribute):
                dotted = _dotted(target)
                if dotted is not None:
                    prev = env.get(dotted, frozenset())
                    if labels - prev:
                        env[dotted] = prev | labels
                # filling a field of a LOCAL object taints the object —
                # `hdr.timestamp = ts; return hdr` must carry the taint
                # out.  `self` is the exception: class-level attribute
                # taint tracks the individual attribute instead, so one
                # tainted field doesn't poison every self access.
                base = target.value
                while isinstance(base, (ast.Attribute, ast.Subscript)):
                    base = base.value
                if isinstance(base, ast.Name) and base.id != "self":
                    prev = env.get(base.id, frozenset())
                    if labels - prev:
                        env[base.id] = prev | labels
                if (
                    cls_q is not None
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                    and _WALL in labels
                ):
                    attrs = self._class_taint.setdefault(cls_q, set())
                    if target.attr not in attrs:
                        attrs.add(target.attr)
                        changed[0] = True
            elif isinstance(target, ast.Subscript):
                assign_to(target.value, labels)
            elif isinstance(target, (ast.Tuple, ast.List)):
                for elt in target.elts:
                    assign_to(elt, labels)

        def walk(stmts) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    # nested defs are outside the summary model (rare
                    # on the paths these rules guard)
                    continue
                elif isinstance(stmt, ast.Assign):
                    labels = ev(stmt.value)
                    for t in stmt.targets:
                        assign_to(t, labels)
                elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                    if stmt.value is not None:
                        assign_to(stmt.target, ev(stmt.value))
                elif isinstance(stmt, ast.Return):
                    if stmt.value is not None:
                        labels = ev(stmt.value)
                        if _WALL in labels and not fn.returns_wallclock:
                            fn.returns_wallclock = True
                            changed[0] = True
                        note_param_summary(labels, fn.param_to_return)
                elif isinstance(stmt, ast.For):
                    assign_to(stmt.target, ev(stmt.iter))
                    walk(stmt.body)
                    walk(stmt.orelse)
                elif isinstance(stmt, (ast.While, ast.If)):
                    ev(stmt.test)
                    walk(stmt.body)
                    walk(stmt.orelse)
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    for item in stmt.items:
                        labels = ev(item.context_expr)
                        if item.optional_vars is not None:
                            assign_to(item.optional_vars, labels)
                    walk(stmt.body)
                elif isinstance(stmt, ast.Try):
                    walk(stmt.body)
                    for h in stmt.handlers:
                        walk(h.body)
                    walk(stmt.orelse)
                    walk(stmt.finalbody)
                elif isinstance(stmt, ast.Expr):
                    ev(stmt.value)
                elif isinstance(stmt, (ast.Raise, ast.Assert)):
                    for child in ast.iter_child_nodes(stmt):
                        if isinstance(child, ast.expr):
                            ev(child)

        # two body iterations: taint born late in a loop body reaches
        # uses earlier in the (next) iteration; env only grows, so the
        # second sweep is the loop-closure
        walk(fn.node.body)
        walk(fn.node.body)
        return changed[0]

    # -- racecheck: lockset-at-access + guarded-by inference ---------------

    def _role_of_ctx(self, mod: ModuleInfo, ctx, ci: ClassInfo | None,
                     types: dict, local: dict | None = None,
                     rebound=()) -> str | None:
        """Lock role of a with-context expression.  None = not a lock;
        _UNKNOWN_LOCK = lock-shaped but unresolvable (suppresses rather
        than fabricates racecheck findings)."""
        dotted = _dotted(ctx)
        if dotted is None:
            return None
        parts = dotted.split(".")
        attr = parts[-1]
        lockish = (
            "lock" in attr.lower()
            or "cond" in attr.lower()
            or attr in ("_idle",)
        )
        if len(parts) == 1:
            # a bare local bound from a field/param chain (`lock =
            # self._mu; with lock:`): resolve the BINDING's qname to its
            # owner's lock role, so these scopes stop degrading to the
            # UNKNOWN lockset (which both hides dirty accesses and
            # excludes clean ones from majority inference)
            bound = (local or {}).get(attr)
            if bound is not None:
                role = self.module_lock_roles.get(bound)
                if role is None and "." in bound:
                    owner_q, _, leaf = bound.rpartition(".")
                    owner = self.classes.get(owner_q)
                    if owner is not None:
                        role = owner.lock_roles.get(leaf)
                if role is not None:
                    # a REBOUND alias (the name is stored more than
                    # once) resolved a lock role through its LAST
                    # binding — earlier with-blocks may hold a
                    # different lock, so suppress rather than credit
                    # the wrong role
                    return _UNKNOWN_LOCK if attr in rebound else role
            role = self.module_lock_roles.get(f"{mod.dotted}.{attr}")
            if role is not None:
                return role
            return _UNKNOWN_LOCK if lockish else None
        head = parts[0]
        owner: ClassInfo | None = None
        if head == "self" and ci is not None:
            if len(parts) == 2:
                owner = ci
            elif len(parts) == 3:
                ft = ci.field_types.get(parts[1])
                owner = self.classes.get(ft) if ft else None
        elif head in types and len(parts) == 2:
            owner = self.classes.get(types[head])
        if owner is not None:
            role = owner.lock_roles.get(attr)
            if role is not None:
                return role
        if lockish:
            return self._attr_role_unique.get(attr) or _UNKNOWN_LOCK
        return None

    def _lockset_pass_all(self) -> None:
        for mod in self.modules.values():
            for fn in mod.functions:
                # __init__ still registers spawn targets and call
                # edges, but its accesses are pre-publication: the
                # object is not shared yet, so they neither need
                # guards nor vote in majority inference
                self._lockset_pass(
                    mod, fn, record_accesses=fn.name != "__init__"
                )

    def _lockset_pass(self, mod: ModuleInfo, fn: FunctionInfo,
                      record_accesses: bool = True) -> None:
        ci = self.classes.get(f"{mod.dotted}.{fn.cls}") if fn.cls else None
        types = getattr(fn, "_types", {})
        local = getattr(fn, "_local_bindings", {})
        held: list[str] = []
        seen_access: set = set()

        def note_field(owner: ClassInfo | None, attr: str, kind: str,
                       line: int) -> None:
            if owner is None or attr in owner.lock_roles:
                return
            if attr not in owner.fields:
                return  # inherited/foreign attr: out of model
            q = f"{owner.qname}.{attr}"
            if q in self.symbols:
                return  # a method, not state
            key = (q, kind, line)
            if key in seen_access:
                return
            seen_access.add(key)
            fn.accesses.append((q, kind, line, frozenset(held)))

        def note_attr(node: ast.Attribute, kind: str) -> None:
            base = node.value
            if isinstance(base, ast.Name):
                if base.id == "self":
                    note_field(ci, node.attr, kind, node.lineno)
                elif base.id in types:
                    note_field(
                        self.classes.get(types[base.id]), node.attr,
                        kind, node.lineno,
                    )
            elif (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
                and ci is not None
            ):
                ft = ci.field_types.get(base.attr)
                if ft is not None:
                    note_field(
                        self.classes.get(ft), node.attr, kind, node.lineno
                    )

        def note_global(node: ast.Name, kind: str) -> None:
            q = f"{mod.dotted}.{node.id}"
            if q not in self.declared_guards:
                return
            key = (q, kind, node.lineno)
            if key in seen_access:
                return
            seen_access.add(key)
            fn.accesses.append((q, kind, node.lineno, frozenset(held)))

        def entry(reason: str, expr) -> None:
            # a bare name may be a locally-defined function (the
            # committer's commit_loop): its symbol lives under this
            # function's `<locals>` scope, not the module scope
            if isinstance(expr, ast.Name):
                scoped = f"{fn.qname}.<locals>.{expr.id}"
                if scoped in self.symbols:
                    self.thread_entries.setdefault(scoped, reason)
                    return
            q = self._resolve_expr(mod, expr, fn.cls, local, types)
            if q is not None and q in self.symbols:
                self.thread_entries.setdefault(q, reason)

        def handle_call(node: ast.Call) -> None:
            q = self.call_resolutions.get(
                (mod.rel, node.lineno, node.col_offset)
            )
            if q is not None:
                fn.call_locks.append((q, frozenset(held)))
            target = self._resolve_expr(mod, node.func, fn.cls, local, types)
            if target in _SPAWN_THREAD_FNS:
                for kw in node.keywords:
                    if kw.arg == "target":
                        entry("thread target", kw.value)
                # lockwatch.spawn_thread(target, ...) takes the target
                # as its first positional (threading.Thread's is
                # `group` — keyword-only there in practice)
                if target != "threading.Thread" and node.args:
                    entry("thread target", node.args[0])
            elif target in _SPAWN_TIMER_FNS:
                for kw in node.keywords:
                    if kw.arg == "function":
                        entry("timer callback", kw.value)
                if len(node.args) >= 2:
                    entry("timer callback", node.args[1])
            elif isinstance(node.func, ast.Attribute):
                if node.func.attr in _SUBMIT_ATTRS and node.args:
                    entry("executor submission", node.args[0])
                elif node.func.attr in _HANDLER_REG_ATTRS:
                    for arg in node.args:
                        if isinstance(arg, (ast.Attribute, ast.Name)):
                            entry(f".{node.func.attr}() handler", arg)

        def scan_expr(expr) -> None:
            if expr is None:
                return
            for node in ast.walk(expr):
                if isinstance(node, ast.Call):
                    handle_call(node)
                elif isinstance(node, ast.Attribute) and isinstance(
                    node.ctx, ast.Load
                ):
                    if record_accesses:
                        note_attr(node, "read")
                elif isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load
                ):
                    if record_accesses:
                        note_global(node, "read")

        def note_target(t) -> None:
            if isinstance(t, ast.Attribute):
                if record_accesses:
                    note_attr(t, "write")
                scan_expr(t.value)
            elif isinstance(t, ast.Subscript):
                v = t.value
                if isinstance(v, ast.Attribute):
                    # mutating a container field IS writing the field
                    if record_accesses:
                        note_attr(v, "write")
                    scan_expr(v.value)
                elif isinstance(v, ast.Name):
                    if record_accesses:
                        note_global(v, "write")
                else:
                    scan_expr(v)
                scan_expr(t.slice)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    note_target(e)
            elif isinstance(t, ast.Starred):
                note_target(t.value)
            elif isinstance(t, ast.Name):
                if record_accesses:
                    note_global(t, "write")

        def walk(stmts) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    pushed = 0
                    for item in stmt.items:
                        scan_expr(item.context_expr)
                        if item.optional_vars is not None:
                            note_target(item.optional_vars)
                        role = self._role_of_ctx(
                            mod, item.context_expr, ci, types, local,
                            getattr(fn, "_rebound", ()),
                        )
                        if role is not None:
                            held.append(role)
                            pushed += 1
                    walk(stmt.body)
                    for _ in range(pushed):
                        held.pop()
                elif isinstance(stmt, ast.Assign):
                    scan_expr(stmt.value)
                    for t in stmt.targets:
                        note_target(t)
                elif isinstance(stmt, ast.AugAssign):
                    scan_expr(stmt.value)
                    note_target(stmt.target)
                elif isinstance(stmt, ast.AnnAssign):
                    scan_expr(stmt.value)
                    note_target(stmt.target)
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    scan_expr(stmt.iter)
                    note_target(stmt.target)
                    walk(stmt.body)
                    walk(stmt.orelse)
                elif isinstance(stmt, (ast.While, ast.If)):
                    scan_expr(stmt.test)
                    walk(stmt.body)
                    walk(stmt.orelse)
                elif isinstance(stmt, ast.Try):
                    walk(stmt.body)
                    for h in stmt.handlers:
                        walk(h.body)
                    walk(stmt.orelse)
                    walk(stmt.finalbody)
                else:
                    for child in ast.iter_child_nodes(stmt):
                        if isinstance(child, ast.expr):
                            scan_expr(child)

        walk(fn.node.body)

    def _racecheck(self) -> None:
        # incoming call edges annotated with the caller's held lockset
        incoming: dict[str, list] = {q: [] for q in self.symbols}
        for fn in self.symbols.values():
            for callee, heldset in fn.call_locks:
                if callee in incoming:
                    incoming[callee].append((fn.qname, heldset))
        # ambient locks: the meet (intersection) over every incoming
        # call path; roots (no resolvable callers) hold nothing.  Used
        # by guard INFERENCE so helper bodies reached only under a lock
        # count as locked sites.
        ambient: dict[str, frozenset | None] = {
            q: (frozenset() if not incoming[q] else None)
            for q in self.symbols
        }
        for _ in range(_MAX_ROUNDS * 4):
            changed = False
            for q, fn in self.symbols.items():
                amb = ambient[q]
                if amb is None:
                    continue
                for callee, heldset in fn.call_locks:
                    if callee not in ambient:
                        continue
                    cand = amb | heldset
                    cur = ambient[callee]
                    new = cand if cur is None else cur & cand
                    if new != cur:
                        ambient[callee] = new
                        changed = True
            if not changed:
                break
        # thread context: the lockset guaranteed on EVERY path from a
        # thread entry point (meet again); functions absent from tctx
        # are not thread-reachable and are never flagged
        tctx: dict[str, frozenset] = {}
        origin: dict[str, str] = {}
        for q, reason in self.thread_entries.items():
            tctx[q] = frozenset()
            origin[q] = f"{q} ({reason})"
        for _ in range(_MAX_ROUNDS * 4):
            changed = False
            for q, fn in list(self.symbols.items()):
                if q not in tctx:
                    continue
                for callee, heldset in fn.call_locks:
                    if callee not in self.symbols:
                        continue
                    cand = tctx[q] | heldset
                    cur = tctx.get(callee)
                    new = cand if cur is None else cur & cand
                    if new != cur:
                        tctx[callee] = new
                        origin.setdefault(callee, origin[q])
                        changed = True
            if not changed:
                break
        # guarded-by map: reviewed declarations first, majority next
        sites: dict[str, list] = {}
        for fn in self.symbols.values():
            amb = ambient.get(fn.qname) or frozenset()
            for field, kind, line, heldset in fn.accesses:
                sites.setdefault(field, []).append(
                    (fn, kind, line, amb | heldset)
                )
        self.guard_map = {}
        for field, ss in sorted(sites.items()):
            declared = self.declared_guards.get(field)
            if declared is not None:
                self.guard_map[field] = {
                    "guard": declared, "source": "declared",
                    "sites": len(ss),
                    "held": sum(
                        1 for _, _, _, ls in ss if declared in ls
                    ),
                }
                continue
            if not any(kind == "write" for _, kind, _, _ in ss):
                continue  # never mutated post-init: cannot race
            counted = [ls for _, _, _, ls in ss if _UNKNOWN_LOCK not in ls]
            if len(counted) < 2:
                continue
            tally: dict[str, int] = {}
            for ls in counted:
                for role in ls:
                    tally[role] = tally.get(role, 0) + 1
            for role, n in sorted(
                tally.items(), key=lambda kv: (-kv[1], kv[0])
            ):
                if n >= 2 and n * 2 > len(counted):
                    self.guard_map[field] = {
                        "guard": role, "source": "inferred",
                        "sites": len(counted), "held": n,
                    }
                break  # only the top role can hold a majority
        # declared guards with no observed sites still surface in the
        # artifact so a stale declaration is visible to reviewers
        for field, role in sorted(self.declared_guards.items()):
            self.guard_map.setdefault(field, {
                "guard": role, "source": "declared", "sites": 0, "held": 0,
            })
        # emission: thread-reachable accesses whose lockset misses the
        # field's guard
        seen: set = set()
        for fn in self.symbols.values():
            T = tctx.get(fn.qname)
            if T is None:
                continue
            for field, kind, line, heldset in fn.accesses:
                g = self.guard_map.get(field)
                if g is None or not g["sites"]:
                    continue
                eff = T | heldset
                if g["guard"] in eff or _UNKNOWN_LOCK in eff:
                    continue
                key = (fn.rel, line)
                if key in seen:
                    continue
                seen.add(key)
                self.race_flows.append(TaintFlow(
                    rel=fn.rel, line=line,
                    message=(
                        f"{kind} of {field} misses its guard lock "
                        f"{g['guard']!r} ({g['source']}, held at "
                        f"{g['held']}/{g['sites']} sites) on a thread "
                        f"path from {origin.get(fn.qname, fn.qname)} — "
                        "hold the guard across this access, move the "
                        "field behind it, or pragma a reviewed benign "
                        "race"
                    ),
                ))
        self.race_flows.sort(key=lambda f: (f.rel, f.line))

    # -- public API --------------------------------------------------------

    def function(self, qname: str) -> FunctionInfo | None:
        return self.symbols.get(qname)

    def summaries(self) -> list[dict]:
        return [
            fn.summary()
            for _, fn in sorted(self.symbols.items())
        ]


__all__ = [
    "Project",
    "FunctionInfo",
    "ModuleInfo",
    "ClassInfo",
    "TaintFlow",
    "CSP_SEAM_ALLOWED",
    "BLOCKING_CALLS",
]
