"""Commit-path v2 parity suite (ISSUE 9 tentpole): the parallel collect
and parallel MVCC prepare stages must be BYTE-IDENTICAL to their serial
counterparts — same flags, same _ItemSink item order and dedup indices,
same MVCC batch contents and namespace order — at every tested pool
width, and the batched recovery replay must reach exactly the state the
per-block replay reached.

Runs WITHOUT the `cryptography` package: a stdlib-only fake MSP/CSP
world (deterministic hash-derived keys and signatures) drives the real
TxValidator through both the native-assisted and pure-Python collect
paths, so the parity pins hold in minimal containers too."""

from __future__ import annotations

import pytest

from fabric_tpu import native, protoutil
from fabric_tpu.common import workpool
from fabric_tpu.common.hashing import sha256 as _sha256
from fabric_tpu.csp.api import VerifyBatchItem
from fabric_tpu.devtools import faultline, invariants, lockwatch
from fabric_tpu.ledger import LedgerProvider
from fabric_tpu.ledger.kvstore import MemKVStore
from fabric_tpu.ledger.statedb import Height, VersionedDB, VersionedValue
from fabric_tpu.ledger.txmgmt import (
    MVCCValidator,
    TxSimulator,
    VALID,
    MVCC_READ_CONFLICT,
)
from fabric_tpu.peer.committer import Committer
from fabric_tpu.peer.txvalidator import TxValidator
from fabric_tpu.protos.common import common_pb2
from fabric_tpu.protos.peer import (
    proposal_pb2,
    proposal_response_pb2,
    transaction_pb2,
)

V = transaction_pb2
CHANNEL = "ppch"


# -- stdlib-only fake crypto world -------------------------------------------


class _FakeKey:
    """Hash-derived public key with the .x/.y ints _ItemSink's dedup
    key and the device marshaling layer expect."""

    __slots__ = ("x", "y")

    def __init__(self, x: int, y: int):
        self.x = x
        self.y = y

    def __eq__(self, other):
        return (self.x, self.y) == (other.x, other.y)

    def __hash__(self):
        return hash((self.x, self.y))


def _key_of(ident_bytes: bytes) -> _FakeKey:
    h = _sha256(b"key:" + ident_bytes)
    return _FakeKey(
        int.from_bytes(h[:16], "big"), int.from_bytes(h[16:], "big")
    )


def _sign(ident_bytes: bytes, digest: bytes) -> bytes:
    k = _key_of(ident_bytes)
    return _sha256(b"sig:%d:%d:" % (k.x, k.y) + digest)


class _FakeIdentity:
    def __init__(self, raw: bytes):
        self.raw = raw
        self.public_key = _key_of(raw)

    def verification_item(self, msg: bytes, sig: bytes) -> VerifyBatchItem:
        return VerifyBatchItem(self.public_key, _sha256(msg), sig)


class _FakeMSPManager:
    """deserialize_identity/validate over raw identity bytes; bytes
    starting with b'badid' refuse to deserialize (the invalid-creator
    lane)."""

    def deserialize_identity(self, raw: bytes) -> _FakeIdentity:
        if bytes(raw).startswith(b"badid"):
            raise ValueError("unknown identity")
        return _FakeIdentity(bytes(raw))

    def validate(self, ident: _FakeIdentity) -> None:
        pass


class _FakePending:
    def __init__(self, items: list, k: int):
        self.items = items
        self._k = k

    def finish(self, mask) -> bool:
        return sum(bool(m) for m in mask) >= self._k


class _FakePolicy:
    """k-of-n endorsement policy with the SignaturePolicy two-phase
    interface (prepare -> pending.items / finish(mask))."""

    def __init__(self, k: int):
        self._k = k

    def prepare(self, signed) -> _FakePending:
        items = [
            VerifyBatchItem(
                _key_of(bytes(sd.identity)),
                sd.digest if sd.digest is not None else _sha256(sd.data),
                sd.signature,
            )
            for sd in signed
        ]
        return _FakePending(items, self._k)


class _FakePolicyManager:
    def __init__(self, k: int = 2):
        self._policy = _FakePolicy(k)

    def get_policy(self, name: str) -> _FakePolicy:
        return self._policy


class _FakeBundle:
    def __init__(self, k: int = 2):
        self.policy_manager = _FakePolicyManager(k)
        self.msp_manager = _FakeMSPManager()


class _FakeCSP:
    """Deterministic verify/hash backend: a signature is valid iff it is
    _sign(identity, digest) for the item's hash-derived key.  Records
    every verify batch so tests can compare _ItemSink contents (order +
    dedup) across collect configurations."""

    def __init__(self):
        self.batches: list[list[VerifyBatchItem]] = []

    def hash_batch(self, msgs):
        return [_sha256(m) for m in msgs]

    def _mask(self, items):
        return [
            bytes(it.signature)
            == _sha256(b"sig:%d:%d:" % (it.key.x, it.key.y) + bytes(it.digest))
            for it in items
        ]

    def verify_batch_async(self, items):
        items = list(items)
        self.batches.append(items)
        mask = self._mask(items)
        return lambda: mask

    def verify_batch(self, items):
        return self.verify_batch_async(items)()


_ENDORSERS = (b"end:org1", b"end:org2", b"end:org3")
_CREATORS = (b"cre:alice", b"cre:bob", b"cre:carol")


def _make_tx(
    key: str,
    value: bytes = b"v",
    cc: str = "ppcc",
    channel: str = CHANNEL,
    creator: bytes = _CREATORS[0],
    endorsers=_ENDORSERS,
    nonce: bytes | None = None,
    txid: str | None = None,
    tx_type: int = common_pb2.ENDORSER_TRANSACTION,
    bad_creator_sig: bool = False,
    tampered_endorsements: int = 0,
    rwset_override: bytes | None = None,
    bad_proposal_hash: bool = False,
    no_endorsements: bool = False,
) -> bytes:
    """One fully well-formed endorser envelope over the fake world, with
    targeted mutations for each failure stage."""
    if rwset_override is not None:
        rwset = rwset_override
    else:
        sim = TxSimulator(VersionedDB(MemKVStore()))
        sim.set_state(cc, key, value)
        rwset = sim.get_tx_simulation_results()
    nonce = nonce if nonce is not None else _sha256(b"nonce:" + key.encode())
    txid = txid if txid is not None else protoutil.compute_tx_id(nonce, creator)
    ext = proposal_pb2.ChaincodeHeaderExtension()
    ext.chaincode_id.name = cc
    chdr = protoutil.make_channel_header(
        tx_type, channel, tx_id=txid,
        extension=ext.SerializeToString(), timestamp=0,
    )
    shdr = protoutil.make_signature_header(creator, nonce)
    chdr_b = chdr.SerializeToString()
    shdr_b = shdr.SerializeToString()
    ccpp_b = proposal_pb2.ChaincodeProposalPayload(
        input=b"input:" + key.encode()
    ).SerializeToString()

    action = proposal_pb2.ChaincodeAction(results=rwset)
    action.chaincode_id.name = cc
    phash = protoutil.proposal_hash2(chdr_b, shdr_b, ccpp_b)
    if bad_proposal_hash:
        phash = b"\x00" * 32
    prp = proposal_response_pb2.ProposalResponsePayload(
        proposal_hash=phash, extension=action.SerializeToString()
    )
    prp_b = prp.SerializeToString()
    endos = []
    if not no_endorsements:
        for j, eb in enumerate(endorsers):
            sig = _sign(eb, _sha256(prp_b + eb))
            if j < tampered_endorsements:
                sig = b"tampered-signature"
            endos.append(
                proposal_response_pb2.Endorsement(endorser=eb, signature=sig)
            )
    cap = transaction_pb2.ChaincodeActionPayload(
        chaincode_proposal_payload=ccpp_b,
        action=transaction_pb2.ChaincodeEndorsedAction(
            proposal_response_payload=prp_b, endorsements=endos
        ),
    )
    tx = transaction_pb2.Transaction(
        actions=[
            transaction_pb2.TransactionAction(payload=cap.SerializeToString())
        ]
    )
    payload_b = common_pb2.Payload(
        header=common_pb2.Header(
            channel_header=chdr_b, signature_header=shdr_b
        ),
        data=tx.SerializeToString(),
    ).SerializeToString()
    env_sig = (
        b"bad-creator-signature"
        if bad_creator_sig
        else _sign(creator, _sha256(payload_b))
    )
    return common_pb2.Envelope(
        payload=payload_b, signature=env_sig
    ).SerializeToString()


def _block_of(env_bytes: list[bytes], num: int = 0,
              prev: bytes = b"") -> common_pb2.Block:
    blk = common_pb2.Block()
    blk.header.number = num
    blk.header.previous_hash = prev
    blk.data.data.extend(env_bytes)
    blk.header.data_hash = protoutil.block_data_hash(blk.data)
    protoutil.init_block_metadata(blk)
    protoutil.set_tx_filter(blk, bytearray(len(env_bytes)))
    return blk


def _copy(blk: common_pb2.Block) -> common_pb2.Block:
    c = common_pb2.Block()
    c.CopyFrom(blk)
    return c


def _mixed_block() -> tuple[common_pb2.Block, dict[int, int]]:
    """A block mixing ~40 valid txs with one lane per failure stage;
    returns (block, {tx index: expected flag})."""
    envs: list[bytes] = []
    expect: dict[int, int] = {}

    def add(env: bytes, flag: int) -> None:
        expect[len(envs)] = flag
        envs.append(env)

    for i in range(40):
        add(
            _make_tx(
                f"k{i}", creator=_CREATORS[i % 3],
                endorsers=_ENDORSERS if i % 4 else _ENDORSERS[:2],
            ),
            V.VALID,
        )
    add(_make_tx("badident", creator=b"badid:x"), V.BAD_CREATOR_SIGNATURE)
    add(_make_tx("badsig", bad_creator_sig=True), V.BAD_CREATOR_SIGNATURE)
    # 1 of 3 endorsements tampered still meets the 2-of-3 policy
    add(_make_tx("tam1", tampered_endorsements=1), V.VALID)
    add(
        _make_tx("tam2", tampered_endorsements=2),
        V.ENDORSEMENT_POLICY_FAILURE,
    )
    dup_nonce = _sha256(b"nonce:dup")
    add(_make_tx("dupA", nonce=dup_nonce), V.VALID)
    add(_make_tx("dupB", nonce=dup_nonce), V.DUPLICATE_TXID)
    add(
        _make_tx("badrw", rwset_override=b"\xff\xff\xff\xff"),
        V.BAD_RWSET,
    )
    add(_make_tx("wrongch", channel="otherch"), V.BAD_CHANNEL_HEADER)
    add(_make_tx("badph", bad_proposal_hash=True), V.BAD_RESPONSE_PAYLOAD)
    add(
        _make_tx("noendo", no_endorsements=True),
        V.ENDORSEMENT_POLICY_FAILURE,
    )
    add(
        _make_tx("badtxid", txid="not-the-binding"), V.BAD_PROPOSAL_TXID
    )
    add(
        _make_tx("badtype", tx_type=common_pb2.MESSAGE), V.UNKNOWN_TX_TYPE
    )
    return _block_of(envs), expect


def _collect_outcome(blk: common_pb2.Block, width: int, pool=None):
    """(flags, verify items, per-tx sink index lists) of one validate
    run at the given collect width."""
    csp = _FakeCSP()
    ledger = LedgerProvider(None).open(CHANNEL)
    v = TxValidator(
        CHANNEL, ledger, _FakeBundle(), csp,
        collect_width=width, collect_pool=pool,
    )
    started = v._start_block(_copy(blk), set())
    block, flags0, works, collect, _envs, bspan = started
    flags = v._finish_block(block, flags0, works, collect, bspan)
    items = csp.batches[0] if csp.batches else []
    index_map = [
        (w.creator_item, [ix for _p, idxs in w.pendings for ix in idxs])
        for w in works
    ]
    return flags, items, index_map, v


# -- collect parity -----------------------------------------------------------


@pytest.mark.parametrize("use_native", [True, False],
                         ids=["native", "pure-python"])
def test_parallel_collect_parity(monkeypatch, use_native):
    """Serial vs parallel collect: identical flags, identical verify-
    item order, identical dedup index assignments at every pool width —
    on both the native-assisted and pure-Python collect paths."""
    if use_native and not native.available():
        pytest.skip("native library unavailable")
    if not use_native:
        monkeypatch.setattr(native, "available", lambda: False)
    blk, expect = _mixed_block()
    base_flags, base_items, base_idx, v0 = _collect_outcome(blk, width=0)
    assert v0.parallel_collect_blocks == 0
    assert base_items, "the mixed block must produce verify items"
    for i, flag in expect.items():
        assert base_flags[i] == flag, (
            f"tx {i}: expected flag {flag}, got {base_flags[i]}"
        )
    for width in (2, 3, 8):
        with workpool.scoped_pool(width, name=f"parity-{width}") as pool:
            flags, items, idx, v = _collect_outcome(
                blk, width=width, pool=pool
            )
        assert v.parallel_collect_blocks == 1, f"width {width} stayed serial"
        assert flags == base_flags, f"width {width} flags diverged"
        assert items == base_items, f"width {width} sink items diverged"
        assert idx == base_idx, f"width {width} dedup indices diverged"


def test_small_block_stays_serial():
    """Blocks under the fan-out threshold must not pay pool overhead."""
    blk = _block_of([_make_tx("only")])
    flags, _items, _idx, v = _collect_outcome(blk, width=8)
    assert flags == [V.VALID]
    assert v.parallel_collect_blocks == 0


def test_collect_tx_chaos_seam(monkeypatch):
    """collect.tx is armable inside the (pooled) collect stage: a
    ctx-free raise rule aborts validation deterministically, and a
    plain delay leaves flags untouched — with the pool active."""
    blk, _expect = _mixed_block()
    with workpool.scoped_pool(3, name="chaos-collect") as pool:
        csp = _FakeCSP()
        ledger = LedgerProvider(None).open(CHANNEL)
        v = TxValidator(
            CHANNEL, ledger, _FakeBundle(), csp,
            collect_width=3, collect_pool=pool,
        )
        with faultline.use_plan({"seed": 5, "faults": [{
            "point": "collect.tx", "action": "raise",
            "error": "OSError", "message": "injected collect fault",
            "nth": 7,
        }]}):
            with pytest.raises(OSError, match="injected collect fault"):
                v.validate(_copy(blk))
            assert any(
                t["point"] == "collect.tx" for t in faultline.trips()
                if t["plan"] != "soak"
            )
        # delays must not change the outcome
        base_flags, base_items, base_idx, _v = _collect_outcome(blk, 0)
        with faultline.use_plan({"seed": 6, "faults": [{
            "point": "collect.tx", "action": "delay", "delay_s": 0.0,
            "every": 9, "count": 50,
        }]}):
            flags, items, idx, _v2 = _collect_outcome(blk, 3, pool=pool)
            assert (flags, items, idx) == (base_flags, base_items, base_idx)


# -- MVCC prepare parity ------------------------------------------------------


def _seeded_db() -> VersionedDB:
    db = VersionedDB(MemKVStore())
    h = Height(1, 0)
    batch: dict = {}
    for ns in ("cc0", "cc1", "cc2"):
        batch[ns] = {
            f"base{i}": VersionedValue(b"b%d" % i, h) for i in range(6)
        }
    # cc2 carries key metadata so the metadata-retention path (and the
    # may_have_metadata-gated write-key preload) is exercised
    from fabric_tpu.ledger.txmgmt import encode_metadata

    batch["cc2"]["base0"] = VersionedValue(
        b"m0", h, encode_metadata({"VALIDATION_PARAMETER": b"pol"})
    )
    db.apply_updates(batch, Height(1, 1))
    return db


def _mvcc_workload(db: VersionedDB):
    """(rwsets, pvt_data) spanning 3 namespaces, in-block conflicts,
    deletes, metadata writes, ranges, and private collections."""
    rwsets: list = []

    def sim() -> TxSimulator:
        return TxSimulator(db)

    # three fat write-only txs (past the fan-out threshold together)
    for t in range(3):
        s = sim()
        for ns in ("cc0", "cc1", "cc2"):
            for i in range(8):
                s.set_state(ns, f"w{t}-{i}", b"x%d" % t)
        rwsets.append(s.get_tx_simulation_results())
    # reads: one consistent, one conflicting with tx0's in-block write
    s = sim()
    s.get_state("cc0", "base0")
    s.set_state("cc1", "r-ok", b"1")
    rwsets.append(s.get_tx_simulation_results())
    s = sim()
    s.get_state("cc0", "w0-0")  # version None committed; tx0 wrote it
    s.set_state("cc0", "r-bad", b"2")
    rwsets.append(s.get_tx_simulation_results())
    # deletes + rewrite, metadata writes on live and absent keys
    s = sim()
    s.delete_state("cc0", "base1")
    s.set_state("cc0", "base2", b"rewritten")
    s.set_state_metadata("cc2", "base1", {"OWNER": b"org1"})
    s.set_state_metadata("cc2", "missing", {"OWNER": b"org2"})
    rwsets.append(s.get_tx_simulation_results())
    # range query over cc1 (phantom-protected)
    s = sim()
    s.get_state_range("cc1", "base0", "base9")
    s.set_state("cc1", "rq", b"3")
    rwsets.append(s.get_tx_simulation_results())
    # private collection: authentic cleartext for tx7, forged for tx8
    s = sim()
    s.set_private_data("cc1", "collA", "p1", b"secret")
    rwsets.append(s.get_tx_simulation_results())
    pvt_good = s.get_pvt_simulation_results()
    s = sim()
    s.set_private_data("cc2", "collB", "p2", b"secret2")
    rwsets.append(s.get_tx_simulation_results())
    pvt_data = {7: pvt_good, 8: b"\x0a\x03bad"}
    return rwsets, pvt_data


def test_parallel_mvcc_prepare_parity():
    """Serial vs fanned-out MVCC prepare: identical flags, identical
    batch contents AND identical namespace insertion order at every
    fan-out width."""
    db = _seeded_db()
    rwsets, pvt_data = _mvcc_workload(db)
    flags0 = [VALID] * len(rwsets)
    serial = MVCCValidator(db, fanout=0)
    base_batch = serial.validate_and_prepare(
        2, list(rwsets), flags0, dict(pvt_data)
    )
    assert serial.parallel_prepare_blocks == 0
    assert flags0[4] == MVCC_READ_CONFLICT  # the in-block stale read
    assert flags0.count(VALID) == len(rwsets) - 1
    # the authentic cleartext landed, the forged one did not
    assert "cc1\x00pvt\x00collA" in base_batch
    assert "cc2\x00pvt\x00collB" not in base_batch
    for width in (2, 3, 8):
        with workpool.scoped_pool(width, name=f"mvcc-{width}") as pool:
            mv = MVCCValidator(db, pool=pool, fanout=width)
            flags = [VALID] * len(rwsets)
            batch = mv.validate_and_prepare(
                2, list(rwsets), flags, dict(pvt_data)
            )
        assert mv.parallel_prepare_blocks == 1, f"width {width} stayed serial"
        assert flags == flags0, f"width {width} flags diverged"
        assert batch == base_batch, f"width {width} batch diverged"
        assert list(batch) == list(base_batch), (
            f"width {width} namespace order diverged"
        )


def test_mvcc_prepare_chaos_seam():
    """mvcc.ns_prepare fires inside the fanned-out prepare; a raise
    rule targeted at one namespace aborts the whole prepare."""
    db = _seeded_db()
    rwsets, pvt_data = _mvcc_workload(db)
    with workpool.scoped_pool(3, name="chaos-mvcc") as pool:
        mv = MVCCValidator(db, pool=pool, fanout=3)
        with faultline.use_plan({"seed": 11, "faults": [{
            "point": "mvcc.ns_prepare", "ctx": {"ns": "cc1"},
            "action": "raise", "error": "OSError",
            "message": "injected prepare fault",
        }]}):
            with pytest.raises(OSError, match="injected prepare fault"):
                mv.validate_and_prepare(
                    2, list(rwsets), [VALID] * len(rwsets), dict(pvt_data)
                )
            trips = [
                t for t in faultline.trips() if t["plan"] != "soak"
            ]
            assert trips and trips[0]["point"] == "mvcc.ns_prepare"
            assert trips[0]["ctx"]["ns"] == "cc1"


# -- batched recovery replay --------------------------------------------------


def _committed_blocks(ledger, n_blocks: int):
    """Commit `n_blocks` multi-namespace blocks per-block; returns the
    writes_by_block model for the invariant oracle."""
    from test_group_commit import _write_block

    model = []
    for num in range(n_blocks):
        items = [
            (ns, f"b{num}-{i}", b"v%d" % num)
            for ns in ("cca", "ccb")
            for i in range(3)
        ]
        ledger.commit(_write_block(ledger, num, items))
        model.append(items)
    return model


@pytest.mark.parametrize("group_size", ["1", "3", "32"])
def test_recovery_replay_equivalence(tmp_path, monkeypatch, group_size):
    """Replay through the WriteBatchCollector group seam reaches the
    same state/history/durable_height as the per-block path at every
    replay group size, judged by the invariant oracle."""
    from test_group_commit import _write_block

    # reference directory: everything committed and flushed per block
    ref_provider = LedgerProvider(str(tmp_path / "ref"))
    ref = ref_provider.open("rec")
    model = _committed_blocks(ref, 3)
    for num in (3, 4, 5, 6):
        items = [
            (ns, f"b{num}-{i}", b"v%d" % num)
            for ns in ("cca", "ccb")
            for i in range(3)
        ]
        ref.commit(_write_block(ref, num, items))
        model.append(items)

    # replay directory: blocks 3..6 land in a group that never flushes
    # (simulated crash) — reopen must replay them through the batched
    # seam
    root = str(tmp_path / f"replay{group_size}")
    provider = LedgerProvider(root)
    led = provider.open("rec")
    _committed_blocks(led, 3)
    group = led.begin_commit_group()
    for num in (3, 4, 5, 6):
        items = [
            (ns, f"b{num}-{i}", b"v%d" % num)
            for ns in ("cca", "ccb")
            for i in range(3)
        ]
        led.commit(_write_block(led, num, items), group=group)
    provider.close()  # crash: group never flushed

    monkeypatch.setenv("FABRIC_TPU_RECOVERY_GROUP", group_size)
    provider2 = LedgerProvider(root)
    led2 = provider2.open("rec")
    violations = invariants.check_ledger(led2, writes_by_block=model)
    assert not violations, [str(x) for x in violations]
    assert led2.height == ref.height == 7
    assert led2.durable_height == 7
    assert led2.state_db.savepoint() == ref.state_db.savepoint()
    for num, items in enumerate(model):
        for ns, key, val in items:
            assert led2.get_state(ns, key) == ref.get_state(ns, key) == val
            assert led2.get_history_for_key(ns, key) == \
                ref.get_history_for_key(ns, key)
    # and the chain continues cleanly from the recovered height
    led2.commit(_write_block(led2, 7, [("cca", "post", b"p")]))
    assert led2.get_state("cca", "post") == b"p"
    provider2.close()
    ref_provider.close()


def test_recovery_replay_coalesces_kv_txns(tmp_path, monkeypatch):
    """The batched replay pays ~one KV transaction per replay group —
    strictly fewer than the per-block-group path over the same tail."""
    from test_group_commit import _write_block
    from fabric_tpu.ledger.kvstore import SqliteKVStore

    def build(root):
        provider = LedgerProvider(root)
        led = provider.open("rec")
        led.commit(_write_block(led, 0, [("cc", "k0", b"v")]))
        group = led.begin_commit_group()
        for num in range(1, 9):
            led.commit(
                _write_block(led, num, [("cc", f"k{num}", b"v")]),
                group=group,
            )
        provider.close()

    def reopen_txns(root, group_size):
        monkeypatch.setenv("FABRIC_TPU_RECOVERY_GROUP", group_size)
        counter = [0]
        real = SqliteKVStore.write_batch

        def wb(store, puts, deletes=()):
            counter[0] += 1
            return real(store, puts, deletes)

        monkeypatch.setattr(SqliteKVStore, "write_batch", wb)
        provider = LedgerProvider(root)
        led = provider.open("rec")
        assert led.height == 9
        assert led.get_state("cc", "k8") == b"v"
        monkeypatch.setattr(SqliteKVStore, "write_batch", real)
        provider.close()
        return counter[0]

    build(str(tmp_path / "a"))
    build(str(tmp_path / "b"))
    per_block = reopen_txns(str(tmp_path / "a"), "1")
    batched = reopen_txns(str(tmp_path / "b"), "32")
    assert batched < per_block, (batched, per_block)


def test_mvcc_adversarial_nul_namespaces():
    """An adversarial rwset may NAME a top-level namespace containing
    the \\x00 separators the derived hash/pvt encodings use.  The
    per-namespace grouping must neither crash nor drop such writes —
    and when a literal namespace COLLIDES with another namespace's
    derived encoding, the prepare must fall back to the old
    single-dict semantics (both writers land in one merged batch dict,
    in tx order) at every fan-out width."""
    db = VersionedDB(MemKVStore())
    evil = "evil\x00hash\x00c"  # literal ns == hash_ns("evil", "c")

    def workload():
        rwsets = []
        s = TxSimulator(db)
        for i in range(20):
            s.set_state(evil, f"lit{i}", b"L")
            s.set_state("cc0", f"pad{i}", b"p")
        rwsets.append(s.get_tx_simulation_results())
        # the colliding derived namespace: private writes in
        # ("evil", "c") hash into the SAME namespace string
        s = TxSimulator(db)
        s.set_private_data("evil", "c", "p1", b"secret")
        for i in range(20):
            s.set_state("cc1", f"q{i}", b"q")
        rwsets.append(s.get_tx_simulation_results())
        return rwsets

    rwsets = workload()
    flags0 = [VALID, VALID]
    serial = MVCCValidator(db, fanout=0)
    base = serial.validate_and_prepare(5, list(rwsets), flags0)
    assert flags0 == [VALID, VALID]
    # the literal writes survived, alongside the hashed write of the
    # colliding derived namespace, in ONE batch dict
    assert base[evil]["lit0"].value == b"L"
    assert base[evil]["lit19"].value == b"L"
    from fabric_tpu.ledger.txmgmt import key_hash

    assert key_hash("p1").hex() in base[evil]
    for width in (2, 4):
        with workpool.scoped_pool(width, name=f"nul-{width}") as pool:
            mv = MVCCValidator(db, pool=pool, fanout=width)
            flags = [VALID, VALID]
            batch = mv.validate_and_prepare(5, list(rwsets), flags)
        assert flags == flags0
        assert batch == base, f"width {width} diverged on NUL namespaces"
        assert list(batch) == list(base)


def test_serial_duplicate_txid_skips_expensive_tail(monkeypatch):
    """The serial collect path must flag a duplicate txid WITHOUT
    paying the transaction-decode/hash/footprint tail (the old
    single-pass behavior); flags still match the parallel path, where
    the dup verdict lands at integration."""
    import fabric_tpu.peer.validation_plugins as vp

    dup_nonce = _sha256(b"nonce:serial-dup")
    envs = [
        _make_tx("sd-a", nonce=dup_nonce),
        _make_tx("sd-b", nonce=dup_nonce),
    ]
    blk = _block_of(envs)
    calls = []
    real = vp.parse_footprint
    monkeypatch.setattr(
        vp, "parse_footprint",
        lambda raw: calls.append(1) or real(raw),
    )
    import fabric_tpu.peer.txvalidator as txv

    monkeypatch.setattr(txv, "parse_footprint", vp.parse_footprint)
    monkeypatch.setattr(native, "available", lambda: False)
    csp = _FakeCSP()
    ledger = LedgerProvider(None).open(CHANNEL)
    v = TxValidator(CHANNEL, ledger, _FakeBundle(), csp, collect_width=0)
    flags = v.validate(_copy(blk))
    assert flags == [V.VALID, V.DUPLICATE_TXID]
    assert len(calls) == 1, "the duplicate's rwset was still parsed"


def test_mvcc_metadata_write_semantics_after_restructure():
    """Hand-computed pins for the pass-1/pass-2 split (not just
    serial-vs-parallel): a metadata write on a live key keeps its value
    and bumps its version; on an in-block-deleted or absent key it is a
    no-op (no version bump — a later read at the committed version
    stays VALID); a value-only write retains committed metadata."""
    from fabric_tpu.ledger.txmgmt import decode_metadata, encode_metadata

    db = VersionedDB(MemKVStore())
    h1 = Height(1, 0)
    db.apply_updates({"cc": {
        "live": VersionedValue(b"v", h1),
        "meta": VersionedValue(b"v", h1, encode_metadata({"A": b"1"})),
        "dele": VersionedValue(b"v", h1),
    }}, Height(1, 1))

    s = TxSimulator(db)
    s.set_state_metadata("cc", "live", {"OWNER": b"org1"})
    rw0 = s.get_tx_simulation_results()
    s = TxSimulator(db)
    s.delete_state("cc", "dele")
    rw1 = s.get_tx_simulation_results()
    s = TxSimulator(db)
    s.set_state_metadata("cc", "dele", {"OWNER": b"org2"})  # deleted: no-op
    s.set_state_metadata("cc", "absent", {"OWNER": b"org3"})  # absent: no-op
    rw2 = s.get_tx_simulation_results()
    s = TxSimulator(db)
    s.set_state("cc", "meta", b"v2")  # value-only: metadata retained
    rw3 = s.get_tx_simulation_results()
    # reads the committed version of 'dele'/'absent' AFTER the metadata
    # no-ops: must stay VALID (a spurious version bump would conflict)
    s = TxSimulator(db)
    s.get_state("cc", "dele")
    s.get_state("cc", "absent")
    s.set_state("cc", "tail", b"t")
    rw4 = s.get_tx_simulation_results()

    flags = [VALID] * 5
    batch = MVCCValidator(db, fanout=0).validate_and_prepare(
        2, [rw0, rw1, rw2, rw3, rw4], flags
    )
    # tx1 deleted 'dele' in-block, so tx4's committed-version read of it
    # conflicts; the metadata no-ops must NOT have bumped 'absent'
    assert flags == [VALID, VALID, VALID, VALID, MVCC_READ_CONFLICT]
    assert decode_metadata(batch["cc"]["live"].metadata) == {
        "OWNER": b"org1"
    }
    assert batch["cc"]["live"].value == b"v"
    assert batch["cc"]["live"].version == Height(2, 0)
    assert batch["cc"]["dele"] is None
    assert "absent" not in batch["cc"]
    assert decode_metadata(batch["cc"]["meta"].metadata) == {"A": b"1"}
    assert batch["cc"]["meta"].value == b"v2"

    # without the in-block delete, the metadata write on the LIVE
    # 'dele' key is a real version bump (a later committed-version read
    # of it must conflict), while the no-op on 'absent' still bumps
    # nothing (a read of it stays VALID)
    s = TxSimulator(db)
    s.get_state("cc", "absent")
    s.set_state("cc", "tail2", b"t")
    rw5 = s.get_tx_simulation_results()
    flags2 = [VALID] * 3
    batch2 = MVCCValidator(db, fanout=0).validate_and_prepare(
        2, [rw2, rw4, rw5], flags2
    )
    assert flags2 == [VALID, MVCC_READ_CONFLICT, VALID]
    assert decode_metadata(batch2["cc"]["dele"].metadata) == {
        "OWNER": b"org2"
    }
    assert "absent" not in batch2["cc"]
    assert "tail2" in batch2["cc"]


# -- sqlite durability knobs --------------------------------------------------


def test_sqlite_durability_knobs(tmp_path, monkeypatch):
    """FABRIC_TPU_SQLITE_SYNC / FABRIC_TPU_WAL_CHECKPOINT reach the
    PRAGMAs; ctor args override env; invalid values refuse loudly."""
    from fabric_tpu.ledger.kvstore import SqliteKVStore

    def pragmas(store):
        sync = store._conn.execute("PRAGMA synchronous").fetchone()[0]
        ckpt = store._conn.execute(
            "PRAGMA wal_autocheckpoint"
        ).fetchone()[0]
        return sync, ckpt

    s = SqliteKVStore(str(tmp_path / "default.db"))
    assert pragmas(s) == (1, 1000)  # NORMAL, sqlite stock threshold
    assert (s.sync_level, s.wal_autocheckpoint) == ("NORMAL", 1000)
    s.close()

    monkeypatch.setenv("FABRIC_TPU_SQLITE_SYNC", "full")
    monkeypatch.setenv("FABRIC_TPU_WAL_CHECKPOINT", "4000")
    s = SqliteKVStore(str(tmp_path / "env.db"))
    assert pragmas(s) == (2, 4000)  # FULL
    s.close()

    s = SqliteKVStore(
        str(tmp_path / "ctor.db"), synchronous="OFF",
        wal_autocheckpoint=0,
    )
    assert pragmas(s) == (0, 0)
    s.close()

    monkeypatch.setenv("FABRIC_TPU_SQLITE_SYNC", "sometimes")
    with pytest.raises(ValueError, match="FABRIC_TPU_SQLITE_SYNC"):
        SqliteKVStore(str(tmp_path / "bad.db"))
    monkeypatch.setenv("FABRIC_TPU_SQLITE_SYNC", "NORMAL")
    monkeypatch.setenv("FABRIC_TPU_WAL_CHECKPOINT", "many")
    with pytest.raises(ValueError, match="FABRIC_TPU_WAL_CHECKPOINT"):
        SqliteKVStore(str(tmp_path / "bad2.db"))


# -- tier-1 smoke: 50-tx pipelined stream, parallel stages on ----------------


def test_smoke_parallel_stream_50tx_depth2():
    """A tiny pipelined validate+commit stream (50 txs, depth 2) with
    parallel collect AND parallel MVCC prepare enabled: green invariant
    oracle, clean lockwatch/threadwatch ledgers, and both stages
    actually fanned out."""
    envs = []
    model = []
    n_txs = 50
    for i in range(n_txs):
        ns = "ppcc" if i % 2 else "ppcc2"
        envs.append(
            _make_tx(f"s{i}", cc=ns, creator=_CREATORS[i % 3])
        )
        model.append((ns, f"s{i}", b"v"))
    with workpool.scoped_pool(2, name="smoke") as pool:
        csp = _FakeCSP()
        provider = LedgerProvider(None)
        ledger = provider.open(CHANNEL)
        validator = TxValidator(
            CHANNEL, ledger, _FakeBundle(), csp,
            collect_width=2, collect_pool=pool,
        )
        # thread the scoped pool through the ledger's commit groups so
        # the MVCC prepare fans out on it too
        import fabric_tpu.ledger.txmgmt as txmgmt

        real_init = txmgmt.MVCCValidator.__init__
        prepared = []

        def init(self, db, p=None, fanout=None):
            real_init(self, db, pool=pool, fanout=2)
            prepared.append(self)

        txmgmt.MVCCValidator.__init__ = init
        try:
            committer = Committer(validator, ledger)
            blk = _block_of(envs, num=0)
            flags = list(committer.store_stream(iter([blk]), depth=2))
        finally:
            txmgmt.MVCCValidator.__init__ = real_init
    assert flags == [[V.VALID] * n_txs]
    assert validator.parallel_collect_blocks >= 1
    assert any(m.parallel_prepare_blocks for m in prepared)
    assert ledger.height == 1
    violations = invariants.check_ledger(
        ledger, writes_by_block=[model]
    )
    assert not violations, [str(x) for x in violations]
    assert not lockwatch.violations
    assert not lockwatch.thread_violations
