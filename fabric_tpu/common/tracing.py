"""tracelens — zero-overhead-when-disabled end-to-end span tracing.

The stage histograms (``commit_stage_seconds``,
``validator_block_stage_duration``) answer "how long does stage X take
in aggregate" but not the questions the commit-path work keeps raising:
which stage sat on the CRITICAL PATH of one slow block, whether
``verify_wait`` overlapped the TPU dispatch or serialized behind it,
and what the pipeline was doing in the seconds before a chaos-oracle
failure.  This module answers those with causally-linked spans, in the
same seam style as faultline/clockskew:

- :func:`span`/:func:`begin` are a module-global load and an ``is
  None`` test when ``FABRIC_TPU_TRACE`` is unset — they return one
  shared no-op object, allocate nothing, and no ring buffer ever
  exists.  Traced and untraced commits are byte-identical (spans only
  observe timing; tests/test_tracing.py pins both contracts).
- Armed, every finished span lands in a process-wide bounded
  ring-buffer **flight recorder** (old spans fall off; the recorder is
  a black box for "what just happened", not a full trace store).
- Span/trace IDs come from a seeded process counter and timestamps
  from the ``clockskew`` provider, so virtual-clock tests produce
  byte-identical traces and same-seed chaos campaigns replay to
  identical span sequences.
- Trace context crosses async hops explicitly: :func:`wire_token`/
  :func:`from_wire` carry it inside RPC frames, :func:`current` +
  :func:`attached` carry it onto committer/workpool/raft-sender
  threads.

Export is Chrome trace-event JSON (``chrome://tracing`` / Perfetto
load it directly): the operations endpoint serves the flight recorder
at ``GET /traces``, ``bench.py --trace-out`` writes the winning stream
pass, and faultfuzz drops a dump next to every repro artifact.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading

from fabric_tpu.devtools import clockskew, knob_registry

_ENV = "FABRIC_TPU_TRACE"
_FALSY = ("", "0", "false", "off", "no")

DEFAULT_CAPACITY = 8192


class SpanContext:
    """The carryable half of a span: (trace_id, span_id).  This is what
    crosses threads and wires — never the Span object itself."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: int, span_id: int):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self) -> str:
        return f"SpanContext({self.trace_id:x}.{self.span_id:x})"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, SpanContext)
            and other.trace_id == self.trace_id
            and other.span_id == self.span_id
        )

    def __hash__(self) -> int:
        return hash((self.trace_id, self.span_id))


class FlightRecorder:
    """Process-wide bounded ring buffer of finished span / instant
    events (Chrome trace-event dicts).  Old events fall off the front —
    the recorder answers "what was the pipeline doing just now", like a
    cockpit flight recorder, not "everything since boot"."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = int(capacity)
        self._buf: collections.deque = collections.deque(
            maxlen=self.capacity
        )
        self._lock = threading.Lock()
        # monotonically increasing per-event cursor: `GET
        # /traces?since=<id>` streams only what landed after a previous
        # poll (netbench polls live nodes incrementally instead of
        # re-downloading the whole recorder each time)
        self._seq = 0

    def record(self, event: dict) -> None:
        with self._lock:
            self._seq += 1
            event["id"] = self._seq
            self._buf.append(event)

    def snapshot(self, since: int | None = None) -> list[dict]:
        with self._lock:
            if since is None:
                return list(self._buf)
            return [ev for ev in self._buf if ev.get("id", 0) > since]

    def snapshot_with_cursor(
        self, since: int | None = None
    ) -> tuple[list[dict], int]:
        """(events after ``since``, cursor) taken under ONE lock — a
        cursor read after a separate snapshot() would advertise events
        recorded in between without containing them, and an incremental
        poller would skip them forever.  A ``since`` AHEAD of the
        current cursor means the recorder was cleared since the caller
        last polled: the stale cursor is invalid, so the full buffer is
        returned and the caller resyncs on the fresh cursor."""
        with self._lock:
            if since is not None and since > self._seq:
                since = None  # stale cursor from before a clear()
            events = (
                list(self._buf) if since is None
                else [ev for ev in self._buf if ev.get("id", 0) > since]
            )
            return events, self._seq

    @property
    def last_event_id(self) -> int:
        with self._lock:
            return self._seq

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self._seq = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)


# the armed recorder; None = tracing disarmed.  EVERY entry point's
# fast path tests only this global (the faultline `_plan` pattern).
_recorder: FlightRecorder | None = None
_state_lock = threading.Lock()

# deterministic id source: a plain counter, reset by reset_ids() so a
# chaos campaign's per-plan traces replay to identical sequences
_ids = [0]
_ids_lock = threading.Lock()

# armed-path consultations — stays 0 while tracing has never been
# armed, which is the zero-overhead acceptance probe
_lookups = [0]

_tls = threading.local()  # .stack: list[Span | _Remote]

# Cross-thread view of the per-thread span stacks, keyed by thread
# ident: the SAME list objects as _tls.stack, so the profscope sampler
# can read another thread's innermost span under the GIL without that
# thread's cooperation.  Only ever populated from _stack(), which runs
# exclusively on armed paths — the disarmed zero-overhead pin holds.
_stacks_by_thread: dict[int, list] = {}


def _stack() -> list:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
        _stacks_by_thread[threading.get_ident()] = s
    return s


def active_span_of(tid: int) -> "Span | None":
    """Innermost live span on thread ``tid``, or None.  A cross-thread
    read for samplers: list snapshot + attribute reads are GIL-atomic,
    and a span that ended between reads reports ``_ended`` and is
    skipped — worst case a sample lands on the parent span, never on a
    corrupt one."""
    stack = _stacks_by_thread.get(tid)
    if not stack:
        return None
    for item in reversed(list(stack)):
        if isinstance(item, Span) and not item._ended:
            return item
    return None


def _next_id() -> int:
    with _ids_lock:
        _ids[0] += 1
        return _ids[0]


class _Remote:
    """Stack marker for a context attached from another thread/process
    hop: parents spans opened in this scope without being a span."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, ctx: SpanContext):
        self.trace_id = ctx.trace_id
        self.span_id = ctx.span_id


class Span:
    """A live span.  Use as a context manager (exception-safe) or via
    explicit :meth:`end`.  ``end`` repairs the thread-local stack: any
    child a crash left open is closed at the same instant and marked
    ``abandoned`` so an injected FaultCrash mid-stage cannot corrupt
    later spans' parenting."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "attrs", "cat",
        "start", "_tid", "_detached", "_ended",
    )

    def __init__(self, name: str, trace_id: int, span_id: int,
                 parent_id: int | None, cat: str, attrs: dict,
                 detached: bool):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.cat = cat
        self.attrs = attrs
        self.start = clockskew.monotonic()
        self._tid = threading.current_thread().name
        self._detached = detached
        self._ended = False

    @property
    def ctx(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def annotate(self, **attrs) -> None:
        self.attrs.update(attrs)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> bool:
        self.end()
        return False

    def end(self) -> None:
        if self._ended:
            return
        rec = _recorder
        end_ts = clockskew.monotonic()
        if not self._detached:
            stack = _stack()
            # repair: close any child an exception left open above us
            while stack:
                top = stack.pop()
                if top is self:
                    break
                if isinstance(top, Span) and not top._ended:
                    top._ended = True
                    top.attrs["abandoned"] = True
                    if rec is not None:
                        rec.record(top._event(end_ts))
        self._ended = True
        if rec is not None:
            rec.record(self._event(end_ts))

    def _event(self, end_ts: float) -> dict:
        args = {
            "trace": f"{self.trace_id:x}",
            "span": f"{self.span_id:x}",
        }
        if self.parent_id is not None:
            args["parent"] = f"{self.parent_id:x}"
        args.update(self.attrs)
        # round, not truncate: 0.01s on a virtual clock must be exactly
        # 10000µs, or determinism tests chase float dust
        ts = round(self.start * 1e6)
        return {
            "ph": "X",
            "name": self.name,
            "cat": self.cat,
            "ts": ts,
            "dur": max(0, round(end_ts * 1e6) - ts),
            "pid": 0,
            "tid": self._tid,
            "args": args,
        }


class _Noop:
    """The shared disarmed span/scope: every method is a no-op and
    every entry point returns THIS singleton — no allocation on the
    disarmed path, pinned by test_tracing."""

    __slots__ = ()
    ctx = None

    def __enter__(self) -> "_Noop":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def annotate(self, **attrs) -> None:
        pass

    def end(self) -> None:
        pass


_NOOP = _Noop()


# -- span entry points --------------------------------------------------------


def begin(name: str, parent: SpanContext | None = None,
          detach: bool = False, cat: str = "span", **attrs):
    """Open a span.  Disarmed: returns the shared no-op.  Armed: the
    parent is `parent` if given, else the innermost span/attached
    context on this thread; a parentless span roots a new trace.
    ``detach=True`` keeps the span OFF the thread-local stack (for
    per-block roots whose children start on other threads/iterations) —
    children then attach via ``attached(span.ctx)`` or ``parent=``."""
    if _recorder is None:
        return _NOOP
    _lookups[0] += 1
    parent_id = None
    trace_id = None
    if parent is not None:
        trace_id = parent.trace_id
        parent_id = parent.span_id
    else:
        stack = _stack()
        if stack:
            top = stack[-1]
            trace_id = top.trace_id
            parent_id = top.span_id
    span_id = _next_id()
    if trace_id is None:
        trace_id = span_id  # root: the trace is named after its root
    sp = Span(name, trace_id, span_id, parent_id, cat, attrs, detach)
    if not detach:
        _stack().append(sp)
    return sp


# `with tracing.span(...)` reads better at call sites; same function.
span = begin


def instant(name: str, **attrs) -> None:
    """Record a zero-duration marker event (faultline trips, lockwatch
    violations, chaos-oracle annotations) parented to the innermost
    active span.  Disarmed: a global load + None test."""
    rec = _recorder
    if rec is None:
        return
    _lookups[0] += 1
    args = dict(attrs)
    stack = _stack()
    if stack:
        top = stack[-1]
        args["trace"] = f"{top.trace_id:x}"
        args["parent"] = f"{top.span_id:x}"
    rec.record({
        "ph": "i",
        "name": name,
        "cat": "mark",
        "ts": round(clockskew.monotonic() * 1e6),
        "pid": 0,
        "tid": threading.current_thread().name,
        "s": "p",
        "args": args,
    })


def annotate(**attrs) -> None:
    """Merge attrs into the innermost active span (no-op when disarmed
    or no span is open)."""
    if _recorder is None:
        return
    stack = _stack()
    if stack and isinstance(stack[-1], Span):
        stack[-1].attrs.update(attrs)


def current() -> SpanContext | None:
    """The innermost active span context on this thread, carryable to
    another thread via :func:`attached`."""
    if _recorder is None:
        return None
    stack = _stack()
    if not stack:
        return None
    top = stack[-1]
    return SpanContext(top.trace_id, top.span_id)


class _Attach:
    __slots__ = ("_ctx",)

    def __init__(self, ctx: SpanContext):
        self._ctx = ctx

    def __enter__(self):
        _stack().append(_Remote(self._ctx))
        return self._ctx

    def __exit__(self, *exc) -> bool:
        stack = _stack()
        if stack:
            stack.pop()
        return False


def attached(ctx: SpanContext | None):
    """Adopt a context carried from another thread/hop for a scope:
    spans opened inside parent to it.  ``attached(None)`` (and the
    disarmed path) is the shared no-op."""
    if _recorder is None or ctx is None:
        return _NOOP
    return _Attach(ctx)


# -- wire propagation ---------------------------------------------------------


def wire_token() -> str | None:
    """The active context as a compact wire token (``trace.span`` hex),
    or None when tracing is disarmed / no span is active — callers emit
    byte-identical frames in that case."""
    ctx = current()
    if ctx is None:
        return None
    return f"{ctx.trace_id:x}.{ctx.span_id:x}"


def from_wire(token: str) -> SpanContext | None:
    """Parse a :func:`wire_token`; malformed tokens are None (a traced
    peer must never be able to crash an untraced server)."""
    try:
        t, _, s = token.partition(".")
        return SpanContext(int(t, 16), int(s, 16))
    except ValueError:
        return None


# Binary-frame piggyback (the gossip TCP transport; the RPC transport
# carries the same token inside its str method field): a traced sender
# prefixes the frame with b"\x01<token>\x01".  Serialized protobuf
# frames always start with a field-tag byte (never 0x01), so receivers
# can ALWAYS strip; untraced senders emit byte-identical frames.  Kept
# HERE beside wire_token/from_wire so the token format has one owner.
FRAME_MARK = b"\x01"


def frame_with_token(data: bytes, ctx: SpanContext | None) -> bytes:
    """Prefix a binary frame with the context's wire token (the frame
    unchanged when ``ctx`` is None — the untraced path)."""
    if ctx is None:
        return data
    token = f"{ctx.trace_id:x}.{ctx.span_id:x}"
    return FRAME_MARK + token.encode("ascii") + FRAME_MARK + data


def split_frame_token(frame: bytes) -> tuple[bytes, SpanContext | None]:
    """(payload, SpanContext | None) — strips the optional trace
    prefix; malformed prefixes fall back to the raw frame so a traced
    peer can never wedge an untraced server."""
    if not frame.startswith(FRAME_MARK):
        return frame, None
    end = frame.find(FRAME_MARK, 1)
    if end < 0:
        return frame, None
    try:
        token = frame[1:end].decode("ascii")
    except UnicodeDecodeError:
        return frame, None
    return frame[end + 1:], from_wire(token)


# -- lifecycle ----------------------------------------------------------------


def enabled() -> bool:
    return _recorder is not None


def recorder() -> FlightRecorder | None:
    return _recorder


def lookup_count() -> int:
    """Armed-path consultations so far — provably 0 while tracing has
    never been armed (the zero-overhead acceptance probe)."""
    return _lookups[0]


def arm(capacity: int = DEFAULT_CAPACITY) -> FlightRecorder:
    """Arm tracing process-wide (idempotent per capacity: re-arming
    replaces the recorder)."""
    global _recorder
    with _state_lock:
        _recorder = FlightRecorder(capacity)
        return _recorder


def disarm() -> None:
    global _recorder
    with _state_lock:
        _recorder = None


def reset_ids(start: int = 0) -> None:
    """Reset the deterministic id counter — a same-seed chaos plan run
    then replays to an identical span sequence."""
    with _ids_lock:
        _ids[0] = int(start)


def reset() -> None:
    """Clear the recorder and the id counter (armed runs that need
    per-pass / per-plan reproducible traces: bench passes, fuzz plans)."""
    rec = _recorder
    if rec is not None:
        rec.clear()
    reset_ids()


@contextlib.contextmanager
def scope(capacity: int = DEFAULT_CAPACITY):
    """Arm tracing for a lexical scope (tests), restoring the previous
    recorder — and the previous id counter — on exit, so a traced test
    leaves the disarmed world exactly as it found it."""
    global _recorder
    with _state_lock:
        prev, _recorder = _recorder, FlightRecorder(capacity)
    with _ids_lock:
        prev_ids = _ids[0]
        _ids[0] = 0
    try:
        yield _recorder
    finally:
        with _state_lock:
            _recorder = prev
        with _ids_lock:
            _ids[0] = prev_ids


# -- export -------------------------------------------------------------------


def export(rec: FlightRecorder | None = None,
           since: int | None = None) -> dict:
    """The flight recorder as a Chrome trace-event document
    (object form: chrome://tracing and Perfetto load it directly).
    ``since`` is an event-id cursor: only events recorded AFTER it are
    included, and ``otherData.last_event_id`` is the cursor for the
    next incremental poll (``GET /traces?since=``); a cursor from
    before a recorder reset is detected (it is ahead of the fresh
    cursor) and answered with the full buffer so the poller resyncs."""
    rec = rec if rec is not None else _recorder
    if rec is not None:
        events, cursor = rec.snapshot_with_cursor(since)
    else:
        events, cursor = [], 0
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "armed": _recorder is not None,
            "source": "fabric_tpu.tracelens",
            "last_event_id": cursor,
        },
    }


def dump_doc(path: str, doc: dict) -> str:
    """Write an already-exported trace document as the canonical
    artifact format (one serialization owned here — faultfuzz repro
    traces and chaos replay dumps route through this)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
        # the dump is usually written on the way down from a failure —
        # push it to the OS now so a crash right after still leaves a
        # complete artifact (this module is a reviewed chaos seam:
        # fabriclint's blocking-io propagation stops here)
        f.flush()
    return path


def dump_to(path: str, rec: FlightRecorder | None = None) -> str:
    """Write :func:`export` as JSON (the chaos-repro trace artifact)."""
    return dump_doc(path, export(rec))


def span_sequence(doc: dict) -> list[tuple]:
    """The determinism view of a trace: (name, trace, span, parent)
    per event in recorded order, timestamps stripped — what same-seed
    campaign runs must reproduce byte-identically."""
    out = []
    for ev in doc.get("traceEvents", []):
        args = ev.get("args", {})
        out.append((
            ev.get("name"), args.get("trace"), args.get("span"),
            args.get("parent"),
        ))
    return out


# -- critical path ------------------------------------------------------------


def critical_path_ms(events, group_attr: str = "block",
                     cat: str = "stage") -> dict[str, float]:
    """Per-stage critical-path milliseconds over `events` (Chrome
    trace dicts), grouped by the ``group_attr`` span attribute (one
    group per block).

    Within each group the chain is built backwards from the latest
    finisher: repeatedly take the span with the latest end among those
    starting before the cursor, attribute ``min(end, cursor) - start``
    to its stage, and move the cursor to its start.  Gaps (no span
    covering the cursor) are skipped.  The result sums each stage's
    contribution across all groups — the "which stage actually gated
    the wall clock" number aggregate percentiles cannot produce."""
    groups: dict = {}
    for ev in events:
        if ev.get("ph", "X") != "X" or ev.get("cat") != cat:
            continue
        g = ev.get("args", {}).get(group_attr)
        if g is None:
            continue
        start = ev["ts"] / 1e3
        groups.setdefault(g, []).append(
            (start, start + ev.get("dur", 0) / 1e3, ev["name"])
        )
    out: dict[str, float] = {}
    for spans in groups.values():
        # deterministic ordering regardless of recorder interleaving
        remaining = sorted(spans, key=lambda s: (-s[1], s[0], s[2]))
        cursor = remaining[0][1]
        while remaining:
            pick = None
            for i, s in enumerate(remaining):
                if s[0] < cursor:
                    pick = i
                    break  # latest end among starts-before-cursor
            if pick is None:
                break
            start, end, name = remaining.pop(pick)
            contrib = min(end, cursor) - start
            if contrib > 0:
                out[name] = out.get(name, 0.0) + contrib
            cursor = min(cursor, start)
    return out


# -- env arming ---------------------------------------------------------------


def _init_from_env() -> None:
    raw = knob_registry.raw(_ENV).strip().lower()
    if raw in _FALSY:
        return
    try:
        cap = int(raw)
    except ValueError:
        cap = DEFAULT_CAPACITY
    arm(cap if cap > 1 else DEFAULT_CAPACITY)


_init_from_env()


__all__ = [
    "SpanContext",
    "FlightRecorder",
    "Span",
    "span",
    "begin",
    "instant",
    "annotate",
    "current",
    "attached",
    "wire_token",
    "from_wire",
    "frame_with_token",
    "split_frame_token",
    "FRAME_MARK",
    "active_span_of",
    "enabled",
    "recorder",
    "lookup_count",
    "arm",
    "disarm",
    "reset",
    "reset_ids",
    "scope",
    "export",
    "dump_doc",
    "dump_to",
    "span_sequence",
    "critical_path_ms",
    "DEFAULT_CAPACITY",
]
