"""Idemix: anonymous credentials (reference /root/reference/idemix/*.go).

The reference implements the CDL credential scheme on the FP256BN pairing
curve via the fabric-amcl library (idemix/util.go, signature.go:243,
credential.go:37).  This package is a ground-up reimplementation of the same
capability surface on BN254 (a standard Barreto-Naehrig curve of the same
256-bit/BN security class, chosen for its widely published, testable
parameters):

- bn254:       field towers Fp/Fp2/Fp6/Fp12, G1/G2, optimal-ate pairing
- issuer:      issuer key generation with proof of well-formedness
               (reference idemix/issuerkey.go)
- credrequest: blinded credential request (idemix/credrequest.go)
- credential:  BBS+-style credential issuance/verification
               (idemix/credential.go)
- signature:   presentation proof with selective disclosure + pseudonym
               (idemix/signature.go) and batched verification (the BN256
               batch-verify baseline configuration)
- nymsignature: pseudonym-only signatures (idemix/nymsignature.go)
- weakbb:      weak Boneh-Boyen signatures (idemix/weakbb.go)
- revocation:  epoch CRI signing/verification (idemix/revocation.go)
"""

from fabric_tpu.idemix.bn254 import (  # noqa: F401
    GROUP_ORDER,
    G1,
    G2,
    g1_gen,
    g2_gen,
    pairing,
    rand_zr,
)
from fabric_tpu.idemix.issuer import IssuerKey, IssuerPublicKey  # noqa: F401
from fabric_tpu.idemix.credential import (  # noqa: F401
    Credential,
    CredRequest,
    new_credential,
    new_cred_request,
)
from fabric_tpu.idemix.signature import Signature, new_signature  # noqa: F401
from fabric_tpu.idemix.nymsignature import (  # noqa: F401
    NymSignature,
    new_nym_signature,
)
