"""cryptogen: generate a network's MSP material from crypto-config.yaml
(reference internal/cryptogen + cmd/cryptogen).

Config schema (subset of the reference's):

    OrdererOrgs:
      - Name: Orderer
        Domain: example.com
        Specs: [{Hostname: orderer}]
    PeerOrgs:
      - Name: Org1
        Domain: org1.example.com
        Template: {Count: 2}
        Users: {Count: 1}

Output layout mirrors the reference:
  <out>/ordererOrganizations/<domain>/{msp, tlsca,
       orderers/<host>.<domain>/{msp, tls}}
  <out>/peerOrganizations/<domain>/{msp, tlsca, peers/.../{msp, tls},
       users/Admin@<domain>/{msp, tls}}

TLS material matches the reference cryptogen (internal/cryptogen/ca +
msp.GenerateLocalMSP tls output): each org gets its own TLS CA; every
node dir gains tls/{ca.crt, server.crt, server.key} and every user dir
tls/{ca.crt, client.crt, client.key}.
"""

from __future__ import annotations

import argparse
import os
import sys

import yaml

from fabric_tpu.common.crypto import CA
from fabric_tpu.msp.config import write_msp_dir


def _emit_node(base: str, ca: CA, name: str, ou: str, node_ous: bool = True,
               tlsca: CA | None = None, server: bool = False):
    pair = ca.issue(name, ous=[ou])
    d = os.path.join(base, "msp")
    write_msp_dir(
        d, ca, node_ous=node_ous,
        signer_cert_pem=pair.cert_pem, signer_key_pem=pair.key_pem,
    )
    if tlsca is not None:
        tdir = os.path.join(base, "tls")
        os.makedirs(tdir, exist_ok=True)
        host = name.split(".", 1)[0]
        tpair = tlsca.issue(
            name, sans=[name, host, "localhost", "127.0.0.1"], client=True, server=True
        )
        stem = "server" if server else "client"
        with open(os.path.join(tdir, "ca.crt"), "wb") as f:
            f.write(tlsca.cert_pem)
        with open(os.path.join(tdir, f"{stem}.crt"), "wb") as f:
            f.write(tpair.cert_pem)
        with open(os.path.join(tdir, f"{stem}.key"), "wb") as f:
            f.write(tpair.key_pem)
    return pair


def _gen_org(out_root: str, kind: str, org: dict) -> None:
    domain = org["Domain"]
    base = os.path.join(out_root, f"{kind}Organizations", domain)
    ca = CA(f"ca.{domain}", domain)
    tlsca = CA(f"tlsca.{domain}", domain)
    # org-level MSP (verification material only)
    write_msp_dir(os.path.join(base, "msp"), ca, node_ous=True)
    os.makedirs(os.path.join(base, "ca"), exist_ok=True)
    from cryptography.hazmat.primitives import serialization

    with open(os.path.join(base, "ca", f"ca.{domain}-cert.pem"), "wb") as f:
        f.write(ca.cert_pem)
    with open(os.path.join(base, "ca", "priv_sk"), "wb") as f:
        f.write(
            ca.key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption(),
            )
        )
    os.makedirs(os.path.join(base, "tlsca"), exist_ok=True)
    with open(
        os.path.join(base, "tlsca", f"tlsca.{domain}-cert.pem"), "wb"
    ) as f:
        f.write(tlsca.cert_pem)

    node_kind = "orderers" if kind == "orderer" else "peers"
    node_ou = "orderer" if kind == "orderer" else "peer"
    hosts = [s["Hostname"] for s in org.get("Specs", [])]
    count = (org.get("Template") or {}).get("Count", 0)
    hosts += [f"peer{i}" for i in range(count)]
    for host in hosts:
        fqdn = f"{host}.{domain}"
        _emit_node(
            os.path.join(base, node_kind, fqdn), ca, fqdn, node_ou,
            tlsca=tlsca, server=True,
        )
    # admin + users
    _emit_node(os.path.join(base, "users", f"Admin@{domain}"), ca,
               f"Admin@{domain}", "admin", tlsca=tlsca)
    for i in range(1, (org.get("Users") or {}).get("Count", 0) + 1):
        _emit_node(os.path.join(base, "users", f"User{i}@{domain}"), ca,
                   f"User{i}@{domain}", "client", tlsca=tlsca)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="cryptogen")
    sub = ap.add_subparsers(dest="cmd", required=True)
    gen = sub.add_parser("generate")
    gen.add_argument("--config", required=True)
    gen.add_argument("--output", default="crypto-config")
    args = ap.parse_args(argv)

    with open(args.config) as f:
        conf = yaml.safe_load(f) or {}
    for org in conf.get("OrdererOrgs") or []:
        _gen_org(args.output, "orderer", org)
    for org in conf.get("PeerOrgs") or []:
        _gen_org(args.output, "peer", org)
    print(f"crypto material written to {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
