"""Fixture-corpus tests for the interprocedural dataflow engine
(ISSUE 4 tentpole): each seeded violation in tests/lint_fixtures/ must
fire at its marked line, and each clean twin must stay quiet — the
false-positive half is what makes the rules deployable at error level.

Fixtures are mapped to synthetic fabric_tpu/ paths so the STRICT
profile applies (the real tree gate skips lint_fixtures/ entirely)."""

from __future__ import annotations

import os

from fabric_tpu.devtools import dataflow
from fabric_tpu.devtools.lint import lint_source, lint_sources

FIXDIR = os.path.join(os.path.dirname(__file__), "lint_fixtures")


def _load(name: str) -> str:
    with open(os.path.join(FIXDIR, name), "r", encoding="utf-8") as f:
        return f.read()


def _fires(violations, rule):
    return [v.line for v in violations
            if v.rule == rule and not v.suppressed]


# -- taint: two assignments + attribute fill into SerializeToString ----------


def test_taint_fires_through_assignments_into_marshal():
    src = _load("fix_taint_dirty.py")
    vs = lint_source(src, "fabric_tpu/orderer/fix_taint_dirty.py")
    lines = _fires(vs, "taint")
    assert len(lines) == 1
    # the violation lands on the marshal (sink), not the source
    assert "SerializeToString" in src.splitlines()[lines[0] - 1]


def test_taint_quiet_on_clean_twin():
    src = _load("fix_taint_clean.py")
    vs = lint_source(src, "fabric_tpu/orderer/fix_taint_clean.py")
    assert vs == []


def test_taint_fires_across_function_boundary():
    srcs = {
        "fabric_tpu/orderer/fix_taint_helper.py":
            _load("fix_taint_helper.py"),
        "fabric_tpu/orderer/fix_taint_top.py":
            _load("fix_taint_top.py"),
    }
    report = lint_sources(srcs)
    by_file: dict[str, list] = {}
    for v in report.unsuppressed:
        by_file.setdefault(v.path, []).append(v)
    # the helper is NOT a violation — its param is the flow, not a leak
    assert "fabric_tpu/orderer/fix_taint_helper.py" not in by_file
    tops = by_file["fabric_tpu/orderer/fix_taint_top.py"]
    assert [v.rule for v in tops] == ["taint"]
    src = srcs["fabric_tpu/orderer/fix_taint_top.py"]
    assert "marshal_at(now)" in src.splitlines()[tops[0].line - 1]
    # and the summary that carried the flow is queryable
    fn = report.project.function(
        "fabric_tpu.orderer.fix_taint_helper.marshal_at"
    )
    assert fn is not None and 0 in fn.param_to_sink


def test_taint_source_sanctioned_by_pragma_does_not_propagate():
    src = _load("fix_taint_dirty.py").replace(
        "    now = time.time()  # the source",
        "    # fabriclint: allow[taint] reviewed: fixture demonstrates a\n"
        "    # sanctioned source stopping propagation\n"
        "    now = time.time()",
    )
    vs = lint_source(src, "fabric_tpu/orderer/fix_taint_dirty.py")
    assert [v for v in vs if not v.suppressed] == []


# -- csp-seam: locals + helpers ----------------------------------------------


def test_seam_fires_via_alias_and_helper():
    src = _load("fix_seam_dirty.py")
    vs = lint_source(src, "fabric_tpu/peer/fix_seam_dirty.py")
    lines = _fires(vs, "csp-seam")
    assert len(lines) == 2
    src_lines = src.splitlines()
    assert "h = hashlib" in src_lines[lines[0] - 1]
    assert "_fingerprint(data)" in src_lines[lines[1] - 1]


def test_seam_quiet_on_clean_twin():
    src = _load("fix_seam_clean.py")
    vs = lint_source(src, "fabric_tpu/peer/fix_seam_clean.py")
    assert vs == []


def test_seam_helper_summary_reports_digest():
    src = _load("fix_seam_dirty.py")
    report = lint_sources({"fabric_tpu/peer/fix_seam_dirty.py": src})
    fn = report.project.function(
        "fabric_tpu.peer.fix_seam_dirty._fingerprint"
    )
    assert fn is not None
    assert fn.returns_digest and fn.uses_hashlib_transitive


# -- lock-discipline: cross-module blocking under commit_lock ----------------


def test_lock_fires_across_modules():
    srcs = {
        "fabric_tpu/ledger/fix_lock_helper.py":
            _load("fix_lock_helper.py"),
        "fabric_tpu/ledger/fix_lock_dirty.py":
            _load("fix_lock_dirty.py"),
    }
    report = lint_sources(srcs)
    hits = [v for v in report.unsuppressed
            if v.rule == "lock-discipline"]
    assert len(hits) == 1
    assert hits[0].path == "fabric_tpu/ledger/fix_lock_dirty.py"
    src = srcs[hits[0].path]
    assert "persist(self._fd)" in src.splitlines()[hits[0].line - 1]


def test_lock_quiet_when_called_outside_the_lock():
    srcs = {
        "fabric_tpu/ledger/fix_lock_helper.py":
            _load("fix_lock_helper.py"),
        "fabric_tpu/ledger/fix_lock_clean.py":
            _load("fix_lock_clean.py"),
    }
    report = lint_sources(srcs)
    assert [v for v in report.unsuppressed
            if v.rule == "lock-discipline"] == []
    # the helper's summary still knows it blocks — the INFORMATION is
    # kept; only the reach-under-lock is a violation
    fn = report.project.function(
        "fabric_tpu.ledger.fix_lock_helper.persist"
    )
    assert fn is not None and fn.blocking_transitive


# -- thread-hygiene ----------------------------------------------------------


def test_thread_hygiene_fires_on_daemon_outside_seam():
    src = _load("fix_thread_dirty.py")
    vs = lint_source(src, "fabric_tpu/gossip/fix_thread_dirty.py")
    lines = _fires(vs, "thread-hygiene")
    assert len(lines) == 1
    assert "threading.Thread" in src.splitlines()[lines[0] - 1]


def test_thread_hygiene_quiet_on_spawn_thread():
    src = _load("fix_thread_clean.py")
    vs = lint_source(src, "fabric_tpu/gossip/fix_thread_clean.py")
    assert vs == []


def test_thread_hygiene_fires_on_daemon_attribute_flip():
    src = (
        "import threading\n"
        "def start(job):\n"
        "    t = threading.Thread(target=job)\n"
        "    t.daemon = True\n"
        "    t.start()\n"
    )
    vs = lint_source(src, "fabric_tpu/gossip/example.py")
    assert _fires(vs, "thread-hygiene") == [4]


def test_thread_hygiene_exempts_the_seam_itself():
    src = _load("fix_thread_dirty.py")
    vs = lint_source(src, "fabric_tpu/devtools/lockwatch.py")
    assert vs == []


# -- summaries: the spawns-thread / acquires-lock facts ----------------------


def test_summaries_expose_thread_and_lock_facts():
    src = (
        "import threading\n"
        "class W:\n"
        "    def go(self):\n"
        "        with self.commit_lock:\n"
        "            pass\n"
        "        t = threading.Thread(target=self.go)\n"
        "        t.start()\n"
    )
    report = lint_sources({"fabric_tpu/gossip/facts.py": src})
    fn = report.project.function("fabric_tpu.gossip.facts.W.go")
    assert fn.spawns_thread
    assert "commit_lock" in fn.acquires_locks


# -- engine internals: import/alias resolution -------------------------------


def test_relative_imports_resolve_into_the_package():
    srcs = {
        "fabric_tpu/ledger/helper.py": (
            "import os\n"
            "def sync(fd):\n"
            "    os.fsync(fd)\n"
        ),
        "fabric_tpu/ledger/user.py": (
            "from .helper import sync\n"
            "class L:\n"
            "    def commit(self, fd):\n"
            "        with self.commit_lock:\n"
            "            sync(fd)\n"
        ),
    }
    report = lint_sources(srcs)
    hits = [v for v in report.unsuppressed
            if v.rule == "lock-discipline"]
    assert [v.path for v in hits] == ["fabric_tpu/ledger/user.py"]


def test_module_dotted_mapping():
    assert dataflow._module_dotted("fabric_tpu/ledger/kvledger.py") == (
        "fabric_tpu.ledger.kvledger"
    )
    assert dataflow._module_dotted("fabric_tpu/csp/__init__.py") == (
        "fabric_tpu.csp"
    )


# -- racecheck: lockset inference + shared-state race detection --------------


def _race_fixture(name: str):
    src = _load(name)
    return src, lint_source(src, f"fabric_tpu/gossip/{name}")


def test_racecheck_fires_on_unguarded_thread_write():
    src, vs = _race_fixture("fix_race_thread_dirty.py")
    lines = _fires(vs, "racecheck")
    assert len(lines) == 1
    assert "fires HERE" in src.splitlines()[lines[0] - 1]
    assert lint_source(
        _load("fix_race_thread_clean.py"),
        "fabric_tpu/gossip/fix_race_thread_clean.py",
    ) == []


def test_racecheck_fires_on_write_outside_guarded_read():
    src, vs = _race_fixture("fix_race_rw_dirty.py")
    lines = _fires(vs, "racecheck")
    assert len(lines) == 1
    assert "fires HERE" in src.splitlines()[lines[0] - 1]
    assert lint_source(
        _load("fix_race_rw_clean.py"),
        "fabric_tpu/gossip/fix_race_rw_clean.py",
    ) == []


def test_racecheck_fires_after_lock_released():
    src, vs = _race_fixture("fix_race_released_dirty.py")
    lines = _fires(vs, "racecheck")
    assert len(lines) == 1
    assert "fires HERE" in src.splitlines()[lines[0] - 1]
    assert lint_source(
        _load("fix_race_released_clean.py"),
        "fabric_tpu/gossip/fix_race_released_clean.py",
    ) == []


def test_racecheck_resolves_annotated_param_call_chain():
    """The acceptance fixture: a violation reached ONLY through an
    attribute call on an annotated parameter is reported — the typed
    resolver keeps the call on the graph."""
    ledger_src = _load("fix_race_typed_ledger.py")
    srcs = {
        "fabric_tpu/orderer/fix_race_typed_ledger.py": ledger_src,
        "fabric_tpu/orderer/fix_race_typed_dirty.py":
            _load("fix_race_typed_dirty.py"),
    }
    report = lint_sources(srcs)
    hits = [v for v in report.unsuppressed if v.rule == "racecheck"]
    assert len(hits) == 1
    assert hits[0].path == "fabric_tpu/orderer/fix_race_typed_ledger.py"
    assert "fires HERE" in ledger_src.splitlines()[hits[0].line - 1]
    # the typed call really resolved (not just a lucky name match)
    key = "fabric_tpu.orderer.fix_race_typed_ledger.FixLedger.bump"
    assert key in report.project.call_resolutions.values()
    # and the worker is a registered thread entry
    assert (
        "fabric_tpu.orderer.fix_race_typed_dirty.HeightPump._run"
        in report.project.thread_entries
    )


def test_racecheck_typed_clean_twin_stays_quiet():
    """Same helper, same latent unguarded write — but the thread path
    goes through the lock-taking method, so nothing fires."""
    srcs = {
        "fabric_tpu/orderer/fix_race_typed_ledger.py":
            _load("fix_race_typed_ledger.py"),
        "fabric_tpu/orderer/fix_race_typed_clean.py":
            _load("fix_race_typed_clean.py"),
    }
    report = lint_sources(srcs)
    assert [v for v in report.unsuppressed if v.rule == "racecheck"] == []


def test_racecheck_guard_map_exposes_inference():
    src = _load("fix_race_thread_dirty.py")
    report = lint_sources({"fabric_tpu/gossip/fix.py": src})
    g = report.project.guard_map[
        "fabric_tpu.gossip.fix.OffersCache._offers"
    ]
    assert g["guard"] == "fixture.offers"
    assert g["source"] == "inferred"
    assert g["held"] == 2 and g["sites"] == 3


def test_racecheck_pragma_suppresses_with_reason():
    src = _load("fix_race_thread_dirty.py").replace(
        '        self._offers["latest"] = 1  # <- racecheck fires HERE',
        "        # fabriclint: allow[racecheck] reviewed: benign "
        "last-write-wins refresh\n"
        '        self._offers["latest"] = 1',
    )
    vs = lint_source(src, "fabric_tpu/gossip/fix.py")
    assert [v for v in vs if not v.suppressed] == []
    assert any(v.rule == "racecheck" and v.suppressed for v in vs)


def test_racecheck_declared_guard_beats_majority():
    """A declared guard flags a lone unlocked thread write even when
    the field has no majority (too few sites for inference)."""
    from fabric_tpu.devtools import dataflow

    src = (
        "from fabric_tpu.devtools.lockwatch import named_lock, "
        "spawn_thread\n"
        "class Reg:\n"
        "    def __init__(self):\n"
        "        self._lock = named_lock('fixture.reg')\n"
        "        self._rows = {}\n"
        "    def start(self):\n"
        "        spawn_thread(target=self._run, kind='worker').start()\n"
        "    def _run(self):\n"
        "        self._rows['k'] = 1\n"
    )
    import ast

    project = dataflow.Project(
        {"fabric_tpu/gossip/reg.py": ast.parse(src)},
        declared_guards={
            "fabric_tpu.gossip.reg.Reg._rows": "fixture.reg"
        },
    )
    assert [f.line for f in project.race_flows] == [9]
    # without the declaration there is no majority and no finding
    project = dataflow.Project(
        {"fabric_tpu/gossip/reg.py": ast.parse(src)}, declared_guards={}
    )
    assert project.race_flows == []


def test_racecheck_sees_positional_spawn_target():
    """spawn_thread(target, ...) without the keyword must still
    register the thread entry — a spelling change must not exempt a
    whole thread from the gate."""
    src = _load("fix_race_thread_dirty.py").replace(
        "target=self._refresh,", "self._refresh,"
    )
    vs = lint_source(src, "fabric_tpu/gossip/fix.py")
    assert len(_fires(vs, "racecheck")) == 1


def test_racecheck_relaxed_profile_exempts_tests():
    src = _load("fix_race_thread_dirty.py")
    assert lint_source(src, "tests/fix_race_thread_dirty.py") == []


# -- gossip taint sinks (payload digests + message marshal) ------------------


def test_gossip_taint_fires_on_digest_and_marshal():
    src = _load("fix_gossip_taint_dirty.py")
    vs = lint_source(src, "fabric_tpu/gossip/fix_gossip_taint_dirty.py")
    lines = _fires(vs, "taint")
    assert len(lines) == 2
    src_lines = src.splitlines()
    assert "sha256(" in src_lines[lines[0] - 1]
    assert "SerializeToString" in src_lines[lines[1] - 1]


def test_gossip_taint_clean_twin_stays_quiet():
    src = _load("fix_gossip_taint_clean.py")
    assert lint_source(
        src, "fabric_tpu/gossip/fix_gossip_taint_clean.py"
    ) == []


def test_gossip_digest_sink_is_scoped_to_gossip():
    """The same wall-clock->seam-digest flow OUTSIDE gossip is not a
    gossip-digest sink (other scopes have their own rules)."""
    src = _load("fix_gossip_taint_dirty.py")
    vs = lint_source(src, "fabric_tpu/comm/fix_gossip_taint_dirty.py")
    lines = _fires(vs, "taint")
    # the serialize sink still fires; the digest line does not
    assert len(lines) == 1
    assert "SerializeToString" in src.splitlines()[lines[0] - 1]


# -- exception-discipline: the faultline seam is transparent -----------------


def test_faultline_point_does_not_launder_swallow():
    """A handler whose only non-trivial statement is a faultline seam
    call still SWALLOWS: the injection point is not a structured
    sentinel, so the violation fires exactly as without it."""
    src = _load("fix_faultline_dirty.py")
    vs = lint_source(src, "fabric_tpu/peer/fix_faultline_dirty.py")
    lines = _fires(vs, "exception-discipline")
    assert len(lines) == 1
    assert "except Exception" in src.splitlines()[lines[0] - 1]


def test_faultline_clean_twin_stays_quiet():
    """...and next to a real structured outcome (logged reason) the
    seam call creates no violation of its own."""
    src = _load("fix_faultline_clean.py")
    vs = lint_source(src, "fabric_tpu/peer/fix_faultline_clean.py")
    assert _fires(vs, "exception-discipline") == []


def test_faultline_seam_keeps_reviewed_pragmas_used():
    """Threading an injection point into an already-pragma'd swallow
    (the deliverclient reconnect loop shape) must keep the pragma USED
    — transparency means the handler still counts as swallowing."""
    src = (
        "from fabric_tpu.devtools import faultline\n"
        "# the arming pin chaos-coverage demands for any new seam\n"
        "PLAN = {'faults': [{'point': 'loop.reconnect',"
        " 'action': 'raise'}]}\n"
        "def run(step):\n"
        "    try:\n"
        "        step()\n"
        "    except Exception:\n"
        "        # fabriclint: allow[exception-discipline] reconnect loop\n"
        "        faultline.point('loop.reconnect')\n"
    )
    vs = lint_source(src, "fabric_tpu/peer/fix_inline.py")
    assert [v for v in vs if not v.suppressed] == []
    assert any(
        v.rule == "exception-discipline" and v.suppressed for v in vs
    )


# -- lock-discipline: the tracing seam is transparent ------------------------


def _real_tracing_source() -> str:
    """The REAL tracelens module source, mapped at its true tree path —
    the transparency being tested is path-scoped to it."""
    import fabric_tpu.common.tracing as _tr

    with open(_tr.__file__, "r", encoding="utf-8") as f:
        return f.read()


def test_tracing_seam_transparent_to_blocking_under_lock():
    """Calling the armed-only tracing seam (whose dump path flushes —
    a blocking summary) while holding the commit lock must NOT fire
    lock-discipline: with tracing disarmed every seam call is a no-op,
    like faultline/clockskew."""
    srcs = {
        "fabric_tpu/common/tracing.py": _real_tracing_source(),
        "fabric_tpu/ledger/fix_tracing_clean.py":
            _load("fix_tracing_clean.py"),
    }
    report = lint_sources(srcs)
    assert [
        v for v in report.unsuppressed
        if v.rule == "lock-discipline"
        and v.path == "fabric_tpu/ledger/fix_tracing_clean.py"
    ] == []
    # the exemption lives in the SUMMARY, not in lost information: the
    # dump path still knows it blocks, it just does not propagate
    fn = report.project.function("fabric_tpu.common.tracing.dump_doc")
    assert fn is not None
    assert fn.blocking and not fn.blocking_transitive


def test_trace_shaped_helper_outside_the_seam_still_fires():
    """The dirty twin: an identically-shaped homegrown dump helper is
    NOT the reviewed seam — blocking-under-commit-lock fires.  The
    exemption is scoped by file path, not by looking trace-like."""
    src = _load("fix_tracing_dirty.py")
    vs = lint_source(src, "fabric_tpu/ledger/fix_tracing_dirty.py")
    lines = _fires(vs, "lock-discipline")
    assert len(lines) == 1
    assert "dump_spans(self._fh" in src.splitlines()[lines[0] - 1]


# -- racecheck PR 8 satellites: closure thread targets + lock aliases --------


def test_racecheck_fires_on_closure_thread_target():
    """A locally-defined function passed to spawn_thread (the
    committer's commit_loop shape) is a real thread entry: its
    unguarded write fires, and the nested symbol is registered under
    the enclosing function's <locals> scope."""
    src, vs = _race_fixture("fix_race_closure_dirty.py")
    lines = _fires(vs, "racecheck")
    assert len(lines) == 1
    assert "fires HERE" in src.splitlines()[lines[0] - 1]
    report = lint_sources(
        {"fabric_tpu/gossip/fix_race_closure_dirty.py": src}
    )
    entry = (
        "fabric_tpu.gossip.fix_race_closure_dirty.StreamPump.start"
        ".<locals>.pump_loop"
    )
    assert entry in report.project.thread_entries


def test_racecheck_closure_clean_twin_stays_quiet():
    assert lint_source(
        _load("fix_race_closure_clean.py"),
        "fabric_tpu/gossip/fix_race_closure_clean.py",
    ) == []


def test_racecheck_real_committer_closure_is_an_entry():
    """The motivating case: the real Committer.store_stream commit_loop
    closure must be on the thread-entry set (and the real tree stays
    clean with it there — the full-tree gate in test_lint_clean covers
    that half)."""
    with open(
        os.path.join(
            os.path.dirname(FIXDIR), "..", "fabric_tpu", "peer",
            "committer.py",
        ), "r", encoding="utf-8",
    ) as f:
        src = f.read()
    report = lint_sources({"fabric_tpu/peer/committer.py": src})
    entry = (
        "fabric_tpu.peer.committer.Committer.store_stream"
        ".<locals>.commit_loop"
    )
    assert entry in report.project.thread_entries


def test_racecheck_fires_on_wrong_lock_through_local_alias():
    """``lock = self._aux; with lock:`` resolves through the local
    binding to the WRONG lock's role — previously the lock-shaped local
    degraded to UNKNOWN and suppressed the finding."""
    src, vs = _race_fixture("fix_race_lockvar_dirty.py")
    lines = _fires(vs, "racecheck")
    assert len(lines) == 1
    assert "fires HERE" in src.splitlines()[lines[0] - 1]


def test_racecheck_lockvar_clean_twin_stays_quiet():
    """The same alias shape binding the CORRECT lock counts as guarded
    — no UNKNOWN suppression, no false positive."""
    assert lint_source(
        _load("fix_race_lockvar_clean.py"),
        "fabric_tpu/gossip/fix_race_lockvar_clean.py",
    ) == []


# -- hbcheck (v4): happens-before racecheck, lock-order, lifecycle -----------


def test_hb_post_start_write_fires():
    """A write AFTER start() races with the spawned thread's read of
    the same field — the new publication-point finding."""
    src, vs = _race_fixture("fix_hb_start_dirty.py")
    lines = _fires(vs, "racecheck")
    assert len(lines) == 1
    assert "fires HERE" in src.splitlines()[lines[0] - 1]
    msg = next(v.message for v in vs
               if v.rule == "racecheck" and not v.suppressed)
    assert "publication point" in msg


def test_hb_pre_start_writes_publish_and_stay_quiet():
    """The clean twin: the same writes BEFORE start() are published by
    the spawn edge — no finding, and the field needs NO guard (source
    ``hb-publish`` in the guard map, every site credited)."""
    src = _load("fix_hb_start_clean.py")
    rel = "fabric_tpu/gossip/fix_hb_start_clean.py"
    assert lint_source(src, rel) == []
    report = lint_sources({rel: src})
    g = report.project.guard_map[
        "fabric_tpu.gossip.fix_hb_start_clean.Pump._batch"
    ]
    assert g["source"] == "hb-publish" and g["guard"] is None
    assert g["hb_safe"] == g["sites"]


def test_hb_event_rearm_fires():
    """clear() on one thread racing a set() on another loses wakeups
    (the PR 11 deliver-client wedge class) — error."""
    src, vs = _race_fixture("fix_hb_event_dirty.py")
    lines = _fires(vs, "racecheck")
    assert len(lines) == 1
    assert "fires HERE" in src.splitlines()[lines[0] - 1]
    msg = next(v.message for v in vs
               if v.rule == "racecheck" and not v.suppressed)
    assert "re-arming" in msg


def test_hb_event_rearm_under_common_lock_stays_quiet():
    assert lint_source(
        _load("fix_hb_event_clean.py"),
        "fabric_tpu/gossip/fix_hb_event_clean.py",
    ) == []


def test_hb_publication_missing_edge_still_fires():
    """The worker's lock-free read with NO publication edge misses the
    inferred guard exactly as in v3 — crediting edges must not blind
    the rule to reads that really are unordered."""
    src, vs = _race_fixture("fix_hb_publish_dirty.py")
    lines = _fires(vs, "racecheck")
    assert len(lines) == 1
    assert "fires HERE" in src.splitlines()[lines[0] - 1]


def test_hb_event_and_queue_publication_credited():
    """The clean twin: the same lock-free worker reads are credited by
    Event set()->wait() and Queue put()->get() edges — quiet, pinned
    down to the exact hb-safe sites."""
    src = _load("fix_hb_publish_clean.py")
    rel = "fabric_tpu/gossip/fix_hb_publish_clean.py"
    assert lint_source(src, rel) == []
    report = lint_sources({rel: src})
    p = report.project
    mod = "fabric_tpu.gossip.fix_hb_publish_clean"
    safe_reads = {
        (field, q.rsplit(".", 1)[-1])
        for (field, kind, _line, q) in p.hb_safe_sites
        if kind == "read" and field.startswith(mod)
    }
    assert (f"{mod}.Feed._snapshot", "_consume") in safe_reads
    assert (f"{mod}.Line._wm", "_drain") in safe_reads
    for field in (f"{mod}.Feed._snapshot", f"{mod}.Line._wm"):
        g = p.guard_map[field]
        assert g["hb_safe"] == g["sites"]


def test_lock_order_cycle_fires_and_names_the_cycle():
    src, vs = _race_fixture("fix_lockorder_dirty.py")
    lines = _fires(vs, "lock-order")
    assert len(lines) == 1
    assert "fires HERE" in src.splitlines()[lines[0] - 1]
    msg = next(v.message for v in vs
               if v.rule == "lock-order" and not v.suppressed)
    assert "fixture.order.a -> fixture.order.b -> fixture.order.a" in msg


def test_lock_order_consistent_order_stays_quiet_with_graph():
    src = _load("fix_lockorder_clean.py")
    rel = "fabric_tpu/gossip/fix_lockorder_clean.py"
    assert lint_source(src, rel) == []
    # the acyclic edge is still IN the graph artifact
    report = lint_sources({rel: src})
    g = report.lock_graph()
    assert "fixture.order.b" in g["edges"]["fixture.order.a"]
    assert "fixture.order.a" not in g["edges"].get("fixture.order.b", {})


def test_lifecycle_unjoined_service_thread_fires():
    src, vs = _race_fixture("fix_lifecycle_dirty.py")
    lines = _fires(vs, "thread-lifecycle")
    assert len(lines) == 1
    assert "fires HERE" in src.splitlines()[lines[0] - 1]


def test_lifecycle_stop_event_and_join_stay_quiet():
    assert lint_source(
        _load("fix_lifecycle_clean.py"),
        "fabric_tpu/gossip/fix_lifecycle_clean.py",
    ) == []


def test_lifecycle_local_list_fan_out_join_is_clean():
    """The joined local-list fan-out (spawn into a local list, join in
    a loop) is a correct pattern the rule must accept — the append
    binds the handle to the LOCAL container and the join loop's loop
    var resolves back to it."""
    src = (
        "from fabric_tpu.devtools.lockwatch import spawn_thread\n"
        "def fan_out(jobs):\n"
        "    threads = []\n"
        "    for job in jobs:\n"
        "        threads.append(spawn_thread(target=job, kind='worker'))\n"
        "    for t in threads:\n"
        "        t.start()\n"
        "    for t in threads:\n"
        "        t.join()\n"
    )
    assert lint_source(src, "fabric_tpu/gossip/fanout.py") == []


def test_lifecycle_pragma_suppresses_with_reason():
    src = _load("fix_lifecycle_dirty.py").replace(
        "        spawn_thread(  # <- thread-lifecycle fires HERE",
        "        # fabriclint: allow[thread-lifecycle] reviewed: fixture\n"
        "        # demonstrates a sanctioned run-forever beacon\n"
        "        spawn_thread(",
    )
    vs = lint_source(src, "fabric_tpu/gossip/fix_lifecycle_dirty.py")
    assert [v for v in vs if not v.suppressed] == []
    assert any(v.rule == "thread-lifecycle" and v.suppressed for v in vs)


def test_closure_sibling_call_resolves_and_fires():
    """ROADMAP satellite: a nested def calling a SIBLING nested def
    stays on the call graph, so the thread target's callees keep their
    lockset facts — the sibling's unguarded write fires."""
    src, vs = _race_fixture("fix_closure_sibling_dirty.py")
    lines = _fires(vs, "racecheck")
    assert len(lines) == 1
    assert "fires HERE" in src.splitlines()[lines[0] - 1]
    report = lint_sources(
        {"fabric_tpu/gossip/fix_closure_sibling_dirty.py": src}
    )
    scope = (
        "fabric_tpu.gossip.fix_closure_sibling_dirty.Roller.launch"
        ".<locals>."
    )
    # the spawn target registered AND the sibling call resolved
    assert f"{scope}pump_loop" in report.project.thread_entries
    assert f"{scope}bump" in report.project.call_resolutions.values()


def test_closure_sibling_clean_twin_stays_quiet():
    assert lint_source(
        _load("fix_closure_sibling_clean.py"),
        "fabric_tpu/gossip/fix_closure_sibling_clean.py",
    ) == []


def test_v4_rules_relaxed_profile_exempts_tests_and_scripts():
    """Tests manage thread lifecycles inline and fixtures seed
    inversions by design: lock-order and thread-lifecycle are off
    under the relaxed profile like racecheck."""
    for name in ("fix_lockorder_dirty.py", "fix_lifecycle_dirty.py",
                 "fix_hb_start_dirty.py"):
        assert lint_source(_load(name), f"tests/{name}") == []


def test_racecheck_rebound_lock_alias_degrades_to_unknown():
    """A lock alias STORED TWICE is ambiguous (the binding map is
    flow-insensitive, last write wins): the correctly guarded first
    with-block must not be flagged just because the alias later binds a
    different lock — rebound aliases degrade to the UNKNOWN lockset."""
    src = (
        "from fabric_tpu.devtools.lockwatch import named_lock, "
        "spawn_thread\n"
        "\n\n"
        "class Table:\n"
        "    def __init__(self):\n"
        "        self._lock = named_lock('fixture.rebound')\n"
        "        self._aux = named_lock('fixture.rebound.aux')\n"
        "        self._rows = {}\n"
        "        self._other = {}\n"
        "\n"
        "    def start(self):\n"
        "        t = spawn_thread(target=self._work, name='w', "
        "kind='worker')\n"
        "        t.start()\n"
        "        return t\n"
        "\n"
        "    def _work(self):\n"
        "        lock = self._lock\n"
        "        with lock:\n"
        "            self._rows['a'] = 1  # correctly guarded\n"
        "        lock = self._aux\n"
        "        with lock:\n"
        "            self._other['b'] = 2\n"
        "\n"
        "    def put(self, k, v):\n"
        "        with self._lock:\n"
        "            self._rows[k] = v\n"
        "\n"
        "    def get(self, k):\n"
        "        with self._lock:\n"
        "            return self._rows.get(k)\n"
    )
    vs = lint_source(src, "fabric_tpu/gossip/fix_rebound_inline.py")
    assert _fires(vs, "racecheck") == []


# -- v5 CFG pass: loop-carried start, branch-dependent lock, early return ----


def test_flow_loopstart_back_edge_write_fires():
    """Start on iteration 1, write on iteration 2: positionally the
    write precedes the start, but the back edge carries it after — the
    v5 acceptance fixture for CFG-ordered happens-before."""
    src = _load("fix_flow_loopstart_dirty.py")
    vs = lint_source(
        src, "fabric_tpu/gossip/fix_flow_loopstart_dirty.py"
    )
    lines = _fires(vs, "racecheck")
    assert len(lines) == 1
    assert "fires HERE" in src.splitlines()[lines[0] - 1]


def test_flow_loopstart_hoisted_publication_quiet():
    src = _load("fix_flow_loopstart_clean.py")
    vs = lint_source(
        src, "fabric_tpu/gossip/fix_flow_loopstart_clean.py"
    )
    assert _fires(vs, "racecheck") == []


def test_flow_branchlock_one_armed_acquire_fires():
    src = _load("fix_flow_branchlock_dirty.py")
    vs = lint_source(
        src, "fabric_tpu/gossip/fix_flow_branchlock_dirty.py"
    )
    lines = _fires(vs, "racecheck")
    assert len(lines) == 1
    assert "fires HERE" in src.splitlines()[lines[0] - 1]


def test_flow_branchlock_try_finally_proven_quiet():
    """The clean twin has NO `with` statement: only the flow lockset
    (explicit acquire → try/finally release as a must-hold dataflow)
    can prove the critical section."""
    src = _load("fix_flow_branchlock_clean.py")
    vs = lint_source(
        src, "fabric_tpu/gossip/fix_flow_branchlock_clean.py"
    )
    assert _fires(vs, "racecheck") == []
    assert _fires(vs, "lock-discipline") == []


def test_flow_earlyret_post_release_write_fires():
    src = _load("fix_flow_earlyret_dirty.py")
    vs = lint_source(
        src, "fabric_tpu/gossip/fix_flow_earlyret_dirty.py"
    )
    lines = _fires(vs, "racecheck")
    assert len(lines) == 1
    assert "fires HERE" in src.splitlines()[lines[0] - 1]


def test_flow_earlyret_try_finally_proven_quiet():
    src = _load("fix_flow_earlyret_clean.py")
    vs = lint_source(
        src, "fabric_tpu/gossip/fix_flow_earlyret_clean.py"
    )
    assert _fires(vs, "racecheck") == []
    assert _fires(vs, "lock-discipline") == []


# -- chaos-coverage: orphaned seam + dead prefix wildcard --------------------


def test_coverage_orphan_seam_and_dead_wildcard_fire():
    """The seeded orphan: a seam no rule can arm fires at the seam
    line, and the wildcard that matches nothing fires at its rule."""
    src = _load("fix_coverage_orphan_dirty.py")
    vs = lint_source(
        src, "fabric_tpu/gossip/fix_coverage_orphan_dirty.py"
    )
    lines = _fires(vs, "chaos-coverage")
    assert len(lines) == 2
    marked = [ln for ln in lines
              if "uncovered: HERE" in src.splitlines()[ln - 1]]
    assert len(marked) == 1
    msgs = [v.message for v in vs
            if v.rule == "chaos-coverage" and not v.suppressed]
    assert any("orphan" in m for m in msgs)


def test_coverage_orphan_exact_pin_quiet():
    src = _load("fix_coverage_orphan_clean.py")
    vs = lint_source(
        src, "fabric_tpu/gossip/fix_coverage_orphan_clean.py"
    )
    assert _fires(vs, "chaos-coverage") == []


# -- v6 rpc-conformance: orphan call site, verb/shape mismatch ---------------


def test_rpc_orphan_call_site_fires_at_the_call():
    src = _load("fix_rpc_orphan_dirty.py")
    vs = lint_source(src, "fabric_tpu/peer/fix_rpc_orphan_dirty.py")
    lines = _fires(vs, "rpc-conformance")
    assert len(lines) == 1
    assert "orphan call site: HERE" in src.splitlines()[lines[0] - 1]
    msgs = [v.message for v in vs if v.rule == "rpc-conformance"]
    assert any("fix.Missing" in m and "no component registers" in m
               for m in msgs)


def test_rpc_orphan_clean_twin_quiet():
    src = _load("fix_rpc_orphan_clean.py")
    vs = lint_source(src, "fabric_tpu/peer/fix_rpc_orphan_clean.py")
    assert vs == []


def test_rpc_verb_shape_mismatch_fires_at_the_call():
    """The register site provably binds a generator (stream-shaped)
    handler; a unary `call` of the method can never frame up."""
    src = _load("fix_rpc_shape_dirty.py")
    vs = lint_source(src, "fabric_tpu/peer/fix_rpc_shape_dirty.py")
    lines = _fires(vs, "rpc-conformance")
    assert len(lines) == 1
    assert "verb/shape mismatch: HERE" in src.splitlines()[lines[0] - 1]
    msgs = [v.message for v in vs if v.rule == "rpc-conformance"]
    assert any("stream-shaped" in m for m in msgs)


def test_rpc_verb_shape_clean_twin_quiet():
    src = _load("fix_rpc_shape_clean.py")
    vs = lint_source(src, "fabric_tpu/peer/fix_rpc_shape_clean.py")
    assert vs == []


def test_rpc_register_without_any_caller_fires_at_the_register():
    """Deleting the probe from the clean twin orphans the handler: the
    violation anchors at the register site."""
    src = _load("fix_rpc_orphan_clean.py")
    src = src[:src.index("def probe")]
    vs = lint_source(src, "fabric_tpu/peer/fix_rpc_orphan_clean.py")
    lines = _fires(vs, "rpc-conformance")
    assert len(lines) == 1
    assert "fix.Ping" in src.splitlines()[lines[0] - 1]
    msgs = [v.message for v in vs if v.rule == "rpc-conformance"]
    assert any("no caller anywhere" in m for m in msgs)


def test_rpc_conformance_disabled_in_relaxed_profile():
    """The same orphan call site under a tests/ path stays quiet: the
    v6 surface rules anchor at production sites only."""
    src = _load("fix_rpc_orphan_dirty.py")
    vs = lint_source(src, "tests/fix_rpc_orphan_dirty.py")
    assert _fires(vs, "rpc-conformance") == []


# -- v6 knob-conformance: unregistered read, helper bypass, README drift -----


def test_knob_unregistered_and_bypass_fire_at_the_reads():
    src = _load("fix_knob_unregistered_dirty.py")
    vs = lint_source(
        src, "fabric_tpu/peer/fix_knob_unregistered_dirty.py"
    )
    lines = _fires(vs, "knob-conformance")
    assert len(lines) == 2
    src_lines = src.splitlines()
    assert "<- unregistered" in src_lines[lines[0] - 1]
    assert "<- helper bypass" in src_lines[lines[1] - 1]
    msgs = [v.message for v in vs if v.rule == "knob-conformance"]
    assert any("FABRIC_TPU_FIXTURE_GHOST" in m for m in msgs)
    assert any("bypasses knob_registry.raw()" in m for m in msgs)


def test_knob_clean_twin_quiet():
    src = _load("fix_knob_unregistered_clean.py")
    vs = lint_source(
        src, "fabric_tpu/peer/fix_knob_unregistered_clean.py"
    )
    assert vs == []


def _registry_project():
    """The real registry module plus a generated reader covering every
    entry, so the dead-entry check cannot fire and the README checks
    are isolated."""
    from fabric_tpu.devtools import knob_registry
    from fabric_tpu.devtools.lint import KNOB_REGISTRY_REL

    with open(os.path.join(
        os.path.dirname(os.path.dirname(__file__)), KNOB_REGISTRY_REL
    ), encoding="utf-8") as f:
        reg_src = f.read()
    reads = "from fabric_tpu.devtools import knob_registry\n\n\n" \
        "def warm():\n" + "".join(
            f'    knob_registry.raw("{name}")\n'
            for name in sorted(knob_registry.KNOBS)
        )
    return {
        KNOB_REGISTRY_REL: reg_src,
        "fabric_tpu/peer/fix_knob_reads.py": reads,
    }


def test_knob_readme_drift_fires_on_stale_table():
    from fabric_tpu.devtools.lint import KNOB_REGISTRY_REL

    report = lint_sources(
        _registry_project(),
        readme_text=_load("fix_knob_readme_dirty.md"),
    )
    vs = [v for v in report.unsuppressed
          if v.rule == "knob-conformance"]
    assert [v.path for v in vs] == [KNOB_REGISTRY_REL]
    assert "drifted" in vs[0].message


def test_knob_readme_generated_table_quiet():
    from fabric_tpu.devtools import knob_registry
    from fabric_tpu.devtools.lint import (
        KNOB_TABLE_BEGIN, KNOB_TABLE_END,
    )

    clean = (
        "# fixture README\n\n" + KNOB_TABLE_BEGIN + "\n"
        + knob_registry.render_table() + KNOB_TABLE_END + "\n"
    )
    report = lint_sources(_registry_project(), readme_text=clean)
    assert [v for v in report.unsuppressed
            if v.rule == "knob-conformance"] == []


def test_knob_readme_missing_marker_block_fires():
    report = lint_sources(
        _registry_project(), readme_text="# no markers here\n"
    )
    msgs = [v.message for v in report.unsuppressed
            if v.rule == "knob-conformance"]
    assert len(msgs) == 1 and "no knob-table marker block" in msgs[0]


def test_knob_dead_registry_entry_fires_at_the_entry():
    """Dropping one knob's generated reader orphans its registry entry;
    the violation anchors at the entry's line in knob_registry.py."""
    from fabric_tpu.devtools import knob_registry
    from fabric_tpu.devtools.lint import KNOB_REGISTRY_REL

    victim = sorted(knob_registry.KNOBS)[0]
    srcs = _registry_project()
    srcs["fabric_tpu/peer/fix_knob_reads.py"] = srcs[
        "fabric_tpu/peer/fix_knob_reads.py"
    ].replace(f'    knob_registry.raw("{victim}")\n', "")
    report = lint_sources(srcs)
    vs = [v for v in report.unsuppressed
          if v.rule == "knob-conformance"]
    assert len(vs) == 1 and vs[0].path == KNOB_REGISTRY_REL
    assert victim in vs[0].message and "dead" in vs[0].message
    reg_lines = srcs[KNOB_REGISTRY_REL].splitlines()
    assert f'"{victim}"' in reg_lines[vs[0].line - 1]


# -- v6 metrics-conformance: consumer without a producer ---------------------


def test_metric_orphan_consumer_fires_at_the_consumer():
    src = _load("fix_metric_consumer_dirty.py")
    vs = lint_source(
        src, "fabric_tpu/devtools/fix_metric_consumer_dirty.py"
    )
    lines = _fires(vs, "metrics-conformance")
    assert len(lines) == 1
    assert "<- orphan consumer" in src.splitlines()[lines[0] - 1]
    msgs = [v.message for v in vs if v.rule == "metrics-conformance"]
    assert any("fix_missing_total" in m and "no producer" in m
               for m in msgs)


def test_metric_consumer_clean_twin_quiet():
    src = _load("fix_metric_consumer_clean.py")
    vs = lint_source(
        src, "fabric_tpu/devtools/fix_metric_consumer_clean.py"
    )
    assert vs == []


def test_metric_unregistered_opts_fires():
    """An Opts construction that never reaches a provider new_* call is
    a configured-but-never-constructed series."""
    src = _load("fix_metric_consumer_clean.py").replace(
        "provider.new_counter(\n        CounterOpts",
        "(\n        CounterOpts",
    )
    vs = lint_source(
        src, "fabric_tpu/devtools/fix_metric_consumer_clean.py"
    )
    msgs = [v.message for v in vs
            if v.rule == "metrics-conformance" and not v.suppressed]
    assert any("never reaches" in m for m in msgs)
