#!/usr/bin/env bash
# Regenerate Python protobuf modules from fabric_tpu/protos/**/*.proto.
# Generated *_pb2.py files are checked in so runtime/test environments
# never need protoc.
set -euo pipefail
cd "$(dirname "$0")/.."
protoc -I. $(find fabric_tpu/protos -name '*.proto') --python_out=.
# package markers for generated dirs
for d in $(find fabric_tpu/protos -type d); do
  [ -f "$d/__init__.py" ] || touch "$d/__init__.py"
done
echo "generated $(find fabric_tpu/protos -name '*_pb2.py' | wc -l) modules"
