"""store_stream: pipelined validate+commit matches sequential
store_block (flags, ledger state, heights); faithful-mode validator
produces identical flags to the optimized path."""

from __future__ import annotations

import pytest

from orgfix import make_org

from fabric_tpu import protoutil
from fabric_tpu.common import configtx_builder as ctx
from fabric_tpu.common.channelconfig import bundle_from_genesis
from fabric_tpu.ledger import LedgerProvider
from fabric_tpu.msp import msp_config_from_ca
from fabric_tpu.peer.committer import Committer
from fabric_tpu.peer.endorser import Endorser
from fabric_tpu.peer.txvalidator import TxValidator
from fabric_tpu.protos.common import common_pb2
from fabric_tpu.protos.peer import proposal_pb2, transaction_pb2

V = transaction_pb2


def _cc(sim, args):
    sim.set_state("strcc", args[0].decode(), args[1])
    return 200, "", b""


@pytest.fixture(scope="module")
def world():
    org = make_org("Org1MSP")
    oorg = make_org("OrdererMSP")
    app = ctx.application_group(
        {"Org1": ctx.org_group("Org1MSP", msp_config_from_ca(org.ca, "Org1MSP"))}
    )
    ordg = ctx.orderer_group(
        {"O": ctx.org_group("OrdererMSP", msp_config_from_ca(oorg.ca, "OrdererMSP"))},
        consensus_type="solo",
    )
    genesis = ctx.genesis_block("strch", ctx.channel_group(app, ordg))
    return org, genesis


def _fresh(org, genesis):
    ledger = LedgerProvider(None).create(genesis)
    bundle = bundle_from_genesis(genesis, org.csp)
    endorser = Endorser(
        "strch", ledger, bundle, org.signer("peer0", role_ou="peer"),
        {"strcc": _cc}, org.csp,
    )
    return ledger, bundle, endorser


def _blocks(endorser, client, n_blocks: int, n_txs: int):
    blocks = []
    for b in range(n_blocks):
        envs = []
        for i in range(n_txs):
            prop, _ = protoutil.create_chaincode_proposal(
                client.serialize(), "strch", "strcc", [b"k%d-%d" % (b, i), b"v"]
            )
            signed = proposal_pb2.SignedProposal(
                proposal_bytes=prop.SerializeToString(),
                signature=client.sign(prop.SerializeToString()),
            )
            resp = endorser.process_proposal(signed)
            env = protoutil.create_signed_tx(prop, client, [resp])
            if i == 1:  # one tampered creator signature per block
                env = common_pb2.Envelope(
                    payload=env.payload, signature=env.signature[:-2] + b"xx"
                )
            envs.append(env)
        blk = common_pb2.Block()
        blk.header.number = b + 1
        blk.data.data.extend(e.SerializeToString() for e in envs)
        while len(blk.metadata.metadata) < 3:
            blk.metadata.metadata.append(b"")
        blocks.append(blk)
    return blocks


def _copies(blocks):
    out = []
    for blk in blocks:
        c = common_pb2.Block()
        c.CopyFrom(blk)
        out.append(c)
    return out


def test_store_stream_matches_sequential(world):
    org, genesis = world
    ledger_a, bundle_a, endorser = _fresh(org, genesis)
    client = org.signer("user1", role_ou="client")
    blocks = _blocks(endorser, client, 4, 3)

    seq_committer = Committer(
        TxValidator("strch", ledger_a, bundle_a, org.csp), ledger_a
    )
    seq = [seq_committer.store_block(b) for b in _copies(blocks)]

    ledger_b, bundle_b, _ = _fresh(org, genesis)
    stream_committer = Committer(
        TxValidator("strch", ledger_b, bundle_b, org.csp), ledger_b
    )
    piped = list(stream_committer.store_stream(iter(_copies(blocks)), depth=3))

    assert piped == seq
    assert ledger_b.height == ledger_a.height == len(blocks) + 1
    for b in range(len(blocks)):
        for i in (0, 2):
            key = "k%d-%d" % (b, i)
            assert ledger_b.get_state("strcc", key) == ledger_a.get_state(
                "strcc", key
            )
    # the tampered tx never landed in state
    assert ledger_b.get_state("strcc", "k0-1") in (None, b"")


def test_store_stream_listener_and_flags(world):
    org, genesis = world
    ledger, bundle, endorser = _fresh(org, genesis)
    client = org.signer("user1", role_ou="client")
    blocks = _blocks(endorser, client, 2, 2)

    seen: list = []
    committer = Committer(TxValidator("strch", ledger, bundle, org.csp), ledger)
    committer.add_commit_listener(lambda blk, flags: seen.append(blk.header.number))
    flags = list(committer.store_stream(iter(blocks), depth=2))
    assert seen == [1, 2]
    for f in flags:
        assert f[0] == V.VALID and f[1] == V.BAD_CREATOR_SIGNATURE


def test_faithful_validator_matches_optimized(world):
    org, genesis = world
    ledger, bundle, endorser = _fresh(org, genesis)
    client = org.signer("user1", role_ou="client")
    blocks = _blocks(endorser, client, 2, 3)

    fast = [
        TxValidator("strch", ledger, bundle, org.csp).validate(b)
        for b in _copies(blocks)
    ]
    faithful = [
        TxValidator("strch", ledger, bundle, org.csp, faithful=True).validate(b)
        for b in _copies(blocks)
    ]
    assert fast == faithful
    for f in fast:
        assert f[1] == V.BAD_CREATOR_SIGNATURE
