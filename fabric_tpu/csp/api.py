"""CSP interface: keys, options, provider protocol.

Modeled on the reference's BCCSP SPI (bccsp/bccsp.go:15-134: Key, KeyGen,
KeyImport, GetKey, Hash, Sign, Verify) plus the batch extension described in
SURVEY.md section 7 step 1: `verify_batch(keys, digests, sigs) -> mask` and
`hash_batch`.  The batch API returns a *per-item* validity mask, never a
single bool: the reference's policy evaluation tolerates invalid endorsements
(common/policies/policy.go:365-402 collects only the valid identities and the
policy may still pass), so a batch must preserve per-signature failure
semantics.
"""

from __future__ import annotations

import abc
import dataclasses
import hashlib
import typing
from typing import Sequence

# Guarded: the interface types (CSP protocol, VerifyBatchItem) must stay
# importable on hosts without the `cryptography` package — policy/
# validation modules import them for type use only.  Key construction
# and (de)serialization raise at call time instead of import time.
# ModuleNotFoundError only: a PRESENT-but-broken cryptography install
# (version mismatch, missing symbol) must surface, not degrade silently.
try:
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import ec
except ModuleNotFoundError as _exc:  # pragma: no cover - minimal hosts
    # Same policy as csp/__init__.py: only cryptography ITSELF missing is
    # forgivable; a missing transitive dep (cffi) is a broken install.
    if (_exc.name or "").split(".")[0] != "cryptography":
        raise
    serialization = ec = None


def _require_crypto() -> None:
    """Called at every key-construction/serialization entry point so a
    minimal host gets an actionable error, not AttributeError on None."""
    if serialization is None:
        raise ImportError(
            "the 'cryptography' package is required for ECDSA key "
            "construction and (de)serialization but is not installed"
        )

# ---------------------------------------------------------------------------
# P-256 domain parameters (NIST FIPS 186-4).
# ---------------------------------------------------------------------------

P256_P = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
P256_A = P256_P - 3
P256_B = 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B
P256_N = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
P256_GX = 0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296
P256_GY = 0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5
P256_HALF_N = P256_N // 2


class Key(abc.ABC):
    """A cryptographic key held by a CSP (reference bccsp/bccsp.go:15-40)."""

    @abc.abstractmethod
    def ski(self) -> bytes:
        """Subject key identifier of this key."""

    @abc.abstractmethod
    def raw(self) -> bytes:
        """Serialized form (public keys: uncompressed EC point, as the
        reference hashes for SKI; private keys: PKCS8 DER)."""

    @property
    def is_private(self) -> bool:
        return False

    def public_key(self) -> "Key":
        raise NotImplementedError


def _point_ski(x: int, y: int) -> bytes:
    # Reference computes SKI = SHA-256 over the uncompressed marshaled point
    # (bccsp/sw/keys.go ecdsaPublicKey.SKI / elliptic.Marshal).
    raw = b"\x04" + x.to_bytes(32, "big") + y.to_bytes(32, "big")
    return hashlib.sha256(raw).digest()


class ECDSAP256PublicKey(Key):
    def __init__(self, key: ec.EllipticCurvePublicKey):
        _require_crypto()
        if not isinstance(key.curve, ec.SECP256R1):
            raise ValueError("only P-256 keys supported")
        self._key = key
        nums = key.public_numbers()
        self.x: int = nums.x
        self.y: int = nums.y
        # fixed-width coordinates, precomputed once: the batch
        # marshaller consumes these per verify item on the hot path
        self.x_bytes: bytes = self.x.to_bytes(32, "big")
        self.y_bytes: bytes = self.y.to_bytes(32, "big")
        self._ski = _point_ski(self.x, self.y)

    def ski(self) -> bytes:
        return self._ski

    def public_key(self) -> "ECDSAP256PublicKey":
        # A public key's public key is itself (reference bccsp/sw/keys
        # ecdsaPublicKey.PublicKey).
        return self

    def raw(self) -> bytes:
        return b"\x04" + self.x.to_bytes(32, "big") + self.y.to_bytes(32, "big")

    def der(self) -> bytes:
        return self._key.public_bytes(
            serialization.Encoding.DER,
            serialization.PublicFormat.SubjectPublicKeyInfo,
        )

    def pem(self) -> bytes:
        return self._key.public_bytes(
            serialization.Encoding.PEM,
            serialization.PublicFormat.SubjectPublicKeyInfo,
        )

    @property
    def crypto_key(self) -> ec.EllipticCurvePublicKey:
        return self._key

    @classmethod
    def from_point(cls, x: int, y: int) -> "ECDSAP256PublicKey":
        _require_crypto()
        nums = ec.EllipticCurvePublicNumbers(x, y, ec.SECP256R1())
        return cls(nums.public_key())

    @classmethod
    def from_der(cls, der: bytes) -> "ECDSAP256PublicKey":
        _require_crypto()
        key = serialization.load_der_public_key(der)
        return cls(key)

    @classmethod
    def from_pem(cls, pem: bytes) -> "ECDSAP256PublicKey":
        _require_crypto()
        key = serialization.load_pem_public_key(pem)
        return cls(key)


class ECDSAP256PrivateKey(Key):
    def __init__(self, key: ec.EllipticCurvePrivateKey):
        _require_crypto()
        if not isinstance(key.curve, ec.SECP256R1):
            raise ValueError("only P-256 keys supported")
        self._key = key
        self._pub = ECDSAP256PublicKey(key.public_key())

    def ski(self) -> bytes:
        return self._pub.ski()

    def raw(self) -> bytes:
        return self._key.private_bytes(
            serialization.Encoding.DER,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        )

    @property
    def is_private(self) -> bool:
        return True

    def public_key(self) -> ECDSAP256PublicKey:
        return self._pub

    @property
    def crypto_key(self) -> ec.EllipticCurvePrivateKey:
        return self._key

    @classmethod
    def generate(cls) -> "ECDSAP256PrivateKey":
        _require_crypto()
        return cls(ec.generate_private_key(ec.SECP256R1()))

    @classmethod
    def from_der(cls, der: bytes) -> "ECDSAP256PrivateKey":
        _require_crypto()
        return cls(serialization.load_der_private_key(der, password=None))

    @classmethod
    def from_pem(cls, pem: bytes) -> "ECDSAP256PrivateKey":
        _require_crypto()
        return cls(serialization.load_pem_private_key(pem, password=None))


# ---------------------------------------------------------------------------
# Signature encoding: DER <-> (r, s), low-S normalization.
# Reference: bccsp/utils/ecdsa.go:39 MarshalECDSASignature, :84 IsLowS,
# :94 ToLowS.  Fabric rejects high-S signatures on verify and always emits
# low-S on sign (signature malleability defense).
# ---------------------------------------------------------------------------


def _der_int(v: int) -> bytes:
    """Minimal DER INTEGER content for a positive integer."""
    raw = v.to_bytes((v.bit_length() + 7) // 8 or 1, "big")
    if raw[0] & 0x80:
        raw = b"\x00" + raw
    return bytes([0x02, len(raw)]) + raw


def _der_read_int(sig: bytes, off: int) -> tuple[int, int]:
    """Strict-DER INTEGER at `off`; returns (value, next offset)."""
    if off + 2 > len(sig) or sig[off] != 0x02:
        raise ValueError("invalid DER signature: expected INTEGER")
    ln = sig[off + 1]
    off += 2
    if ln == 0 or ln > 0x7F or off + ln > len(sig):
        raise ValueError("invalid DER signature: bad integer length")
    raw = sig[off:off + ln]
    if raw[0] & 0x80:
        raise ValueError("invalid DER signature: negative integer")
    if ln > 1 and raw[0] == 0 and not raw[1] & 0x80:
        raise ValueError("invalid DER signature: non-minimal integer")
    return int.from_bytes(raw, "big"), off + ln


def marshal_ecdsa_signature(r: int, s: int) -> bytes:
    """DER ECDSA-Sig-Value encoding — pure stdlib (a P-256 r/s pair
    fits short-form lengths), so signature marshaling works on minimal
    hosts without the `cryptography` package."""
    body = _der_int(r) + _der_int(s)
    if len(body) > 0x7F:
        # enforce the short-form assumption instead of silently
        # emitting malformed DER for oversized integers
        raise ValueError("r/s too large for short-form DER encoding")
    return bytes([0x30, len(body)]) + body


def unmarshal_ecdsa_signature(sig: bytes) -> tuple[int, int]:
    """DER-decode a signature. Raises ValueError on malformed input or
    non-positive r/s (reference bccsp/utils/ecdsa.go:47-62).  Strict:
    trailing bytes, non-minimal integers, and negatives are rejected,
    matching the asn1 backends the sw provider verifies with."""
    if len(sig) < 2 or sig[0] != 0x30:
        raise ValueError("invalid DER signature: expected SEQUENCE")
    if sig[1] > 0x7F or 2 + sig[1] != len(sig):
        raise ValueError("invalid DER signature: bad sequence length")
    r, off = _der_read_int(sig, 2)
    s, off = _der_read_int(sig, off)
    if off != len(sig):
        raise ValueError("invalid DER signature: trailing bytes")
    if r <= 0 or s <= 0:
        raise ValueError("invalid signature: r and s must be positive")
    return r, s


def is_low_s(s: int) -> bool:
    return s <= P256_HALF_N


def to_low_s(s: int) -> int:
    return P256_N - s if s > P256_HALF_N else s


# ---------------------------------------------------------------------------
# Batch verify item.
# ---------------------------------------------------------------------------


class VerifyBatchItem(typing.NamedTuple):
    """One (public key, digest, signature) triple for batched
    verification.  A NamedTuple, not a dataclass: the validator creates
    one per creator/endorsement lane (thousands per block), and tuple
    construction runs in C at roughly half the dataclass __init__
    cost — this is hot-path object churn, measured in profile_host."""

    key: ECDSAP256PublicKey
    digest: bytes  # 32-byte SHA-256 digest of the signed message
    signature: bytes  # DER-encoded (r, s)


class CSP(abc.ABC):
    """Provider protocol (reference bccsp/bccsp.go:90-134), plus batch ops."""

    @abc.abstractmethod
    def key_gen(self) -> ECDSAP256PrivateKey: ...

    @abc.abstractmethod
    def key_import(self, raw: bytes, private: bool = False) -> Key: ...

    @abc.abstractmethod
    def get_key(self, ski: bytes) -> Key: ...

    @abc.abstractmethod
    def hash(self, msg: bytes) -> bytes: ...

    @abc.abstractmethod
    def sign(self, key: Key, digest: bytes) -> bytes: ...

    @abc.abstractmethod
    def verify(self, key: Key, signature: bytes, digest: bytes) -> bool: ...

    # -- batch extension (the TPU seam) ------------------------------------

    @abc.abstractmethod
    def hash_batch(self, msgs: Sequence[bytes]) -> list[bytes]: ...

    @abc.abstractmethod
    def verify_batch(self, items: Sequence[VerifyBatchItem]) -> list[bool]: ...

    def verify_batch_async(self, items: Sequence[VerifyBatchItem]):
        """Dispatch a batch verify and return a zero-arg collector.

        Device providers override this to return BEFORE the device
        finishes, so callers can overlap host work for the next batch
        with the device's current one (the block-pipeline mode of the
        txvalidator).  The default computes eagerly — correct for host
        providers, which have nothing to overlap."""
        result = self.verify_batch(items)
        return lambda: result


__all__ = [
    "CSP",
    "Key",
    "ECDSAP256PublicKey",
    "ECDSAP256PrivateKey",
    "VerifyBatchItem",
    "marshal_ecdsa_signature",
    "unmarshal_ecdsa_signature",
    "is_low_s",
    "to_low_s",
    "P256_P",
    "P256_A",
    "P256_B",
    "P256_N",
    "P256_GX",
    "P256_GY",
    "P256_HALF_N",
]
